"""Flash-attention kernel parity vs the einsum oracle (fwd + grads), run in
Pallas interpret mode on CPU (SURVEY §7 hard-part #4: correctness vs the
oracle first, performance on hardware second)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mingpt_distributed_tpu.config import GPTConfig
from mingpt_distributed_tpu.models import gpt
from mingpt_distributed_tpu.ops import attention as attn_ops
from mingpt_distributed_tpu.ops import flash_attention as flash


def qkv(b=2, t=128, h=4, kv=None, hd=32, seed=0, dtype=jnp.float32):
    kv = kv or h
    ks = jax.random.split(jax.random.key(seed), 3)
    q = jax.random.normal(ks[0], (b, t, h, hd), dtype)
    k = jax.random.normal(ks[1], (b, t, kv, hd), dtype)
    v = jax.random.normal(ks[2], (b, t, kv, hd), dtype)
    return q, k, v


def test_forward_parity():
    q, k, v = qkv()
    want = attn_ops.causal_attention(q, k, v)
    got = flash.causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_forward_parity_gqa():
    q, k, v = qkv(h=4, kv=2)
    want = attn_ops.causal_attention(q, k, v)
    got = flash.causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_forward_parity_multiblock():
    # T=256 -> block 128 x 2: exercises the streaming-softmax accumulation
    q, k, v = qkv(t=256, seed=3)
    want = attn_ops.causal_attention(q, k, v)
    got = flash.causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_gradient_parity():
    q, k, v = qkv(t=128, seed=5)

    def loss(fn, q, k, v):
        return jnp.sum(jnp.square(fn(q, k, v)))

    g_want = jax.grad(lambda *a: loss(attn_ops.causal_attention, *a),
                      argnums=(0, 1, 2))(q, k, v)
    g_got = jax.grad(lambda *a: loss(flash.causal_attention, *a),
                     argnums=(0, 1, 2))(q, k, v)
    for want, got, name in zip(g_want, g_got, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4,
            err_msg=f"d{name} mismatch",
        )


def test_gradient_parity_gqa_multiblock():
    q, k, v = qkv(t=256, h=4, kv=1, seed=7)

    def loss(fn, q, k, v):
        return jnp.sum(jnp.square(fn(q, k, v)))

    g_want = jax.grad(lambda *a: loss(attn_ops.causal_attention, *a),
                      argnums=(0, 1, 2))(q, k, v)
    g_got = jax.grad(lambda *a: loss(flash.causal_attention, *a),
                     argnums=(0, 1, 2))(q, k, v)
    for want, got, name in zip(g_want, g_got, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4,
            err_msg=f"d{name} mismatch",
        )


def test_gradient_parity_long_sequence():
    """T=1024 -> 512-blocks streamed via the grid (the FA2 re-tiling): the
    per-cell VMEM footprint must not depend on T, and the scratch-carried
    online softmax must stay exact across many k blocks."""
    q, k, v = qkv(b=1, t=1024, h=2, seed=11)

    def loss(fn, q, k, v):
        return jnp.sum(jnp.square(fn(q, k, v)))

    want = attn_ops.causal_attention(q, k, v)
    got = flash.causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    g_want = jax.grad(lambda *a: loss(attn_ops.causal_attention, *a),
                      argnums=(0, 1, 2))(q, k, v)
    g_got = jax.grad(lambda *a: loss(flash.causal_attention, *a),
                     argnums=(0, 1, 2))(q, k, v)
    for want, got, name in zip(g_want, g_got, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=5e-4, atol=5e-4,
            err_msg=f"d{name} mismatch",
        )


def test_fallback_paths_route_to_oracle():
    # dropout active -> einsum fallback (still correct, just not flash)
    q, k, v = qkv(t=64)
    out = flash.causal_attention(
        q, k, v, attn_pdrop=0.5, dropout_key=jax.random.key(0),
        deterministic=False,
    )
    assert out.shape == q.shape
    # decode-style (q_len 1 vs cache 64) -> fallback with kv_offset
    out = flash.causal_attention(q[:, :1], k, v, kv_offset=63)
    assert out.shape == (2, 1, 4, 32)
    # odd T -> fallback
    out = flash.causal_attention(q[:, :37], k[:, :37], v[:, :37])
    want = attn_ops.causal_attention(q[:, :37], k[:, :37], v[:, :37])
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_model_forward_with_flash_matches_einsum():
    """End-to-end: gpt_config.attention=flash must reproduce einsum logits."""
    base = dict(
        n_layer=2, n_head=2, n_embd=32, vocab_size=50, block_size=128,
        embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0, dtype="float32",
    )
    cfg_e = GPTConfig.make(**base, attention="einsum")
    cfg_f = GPTConfig.make(**base, attention="flash")
    params = gpt.init(jax.random.key(0), cfg_e)
    tokens = jax.random.randint(jax.random.key(1), (2, 128), 0, 50)
    le, _ = gpt.forward(params, tokens, cfg_e, targets=tokens)
    lf, _ = gpt.forward(params, tokens, cfg_f, targets=tokens)
    np.testing.assert_allclose(np.asarray(lf), np.asarray(le),
                               rtol=2e-4, atol=2e-4)


def _dense_noncausal(q, k, v):
    """Non-causal reference: softmax(QK^T/sqrt(hd))V + its log-sum-exp."""
    hd = q.shape[-1]
    s = jnp.einsum("bthd,bshd->bhts", q, k,
                   preferred_element_type=jnp.float32) / jnp.sqrt(
                       jnp.asarray(hd, jnp.float32))
    lse = jax.nn.logsumexp(s, axis=-1)  # (B, H, T)
    out = jnp.einsum("bhts,bshd->bthd", jax.nn.softmax(s, -1), v)
    return out.astype(q.dtype), lse


def test_flash_with_lse_noncausal_parity():
    """The non-causal kernel mode (ring attention's off-diagonal hops):
    out and lse both match the dense reference."""
    import math

    b, t, h, hd = 2, 256, 2, 32
    q, k, v = qkv(b=b, t=t, h=h, hd=hd, seed=5)
    want_out, want_lse = _dense_noncausal(q, k, v)

    to_bh = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, t, hd)
    out, lse = flash.flash_with_lse(
        to_bh(q), to_bh(k), to_bh(v), 1.0 / math.sqrt(hd), 128, False
    )
    out = out.reshape(b, h, t, hd).transpose(0, 2, 1, 3)
    lse = lse.reshape(b, h, t)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want_out),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(want_lse),
                               rtol=1e-5, atol=1e-5)


def test_flash_with_lse_cotangent():
    """Gradients that flow through BOTH outputs (out and lse) match the
    dense reference — the lse cotangent folds into the delta term."""
    import math

    b, t, h, hd = 1, 128, 2, 16
    q, k, v = qkv(b=b, t=t, h=h, hd=hd, seed=9)
    to_bh = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, t, hd)

    def loss_flash(q, k, v):
        out, lse = flash.flash_with_lse(
            to_bh(q), to_bh(k), to_bh(v), 1.0 / math.sqrt(hd), 128, False
        )
        return (out.astype(jnp.float32) ** 2).sum() + (lse * 0.3).sum()

    def loss_dense(q, k, v):
        out, lse = _dense_noncausal(q, k, v)
        return (out.astype(jnp.float32) ** 2).sum() + (lse * 0.3).sum()

    g_f = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_d = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_f, g_d):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4)


# --- native-layout (B, T, D) kernel path -----------------------------------


def _both_layouts(q, k, v, monkeypatch, **kw):
    """Run flash.causal_attention with the btd path and the transpose path."""
    monkeypatch.setenv("FLASH_LAYOUT", "auto")
    got_btd = flash.causal_attention(q, k, v, **kw)
    monkeypatch.setenv("FLASH_LAYOUT", "bh")
    got_bh = flash.causal_attention(q, k, v, **kw)
    return got_btd, got_bh


def test_btd_pack_table():
    assert flash._btd_pack(12, 64) == 2   # gpt2
    assert flash._btd_pack(4, 32) == 4
    assert flash._btd_pack(32, 128) == 1  # llama-shaped
    assert flash._btd_pack(3, 64) is None   # odd head count can't pair
    assert flash._btd_pack(4, 48) is None   # 48 doesn't divide 128


def test_btd_forward_and_grad_parity(monkeypatch):
    """The native-layout path must agree with the transpose path AND the
    oracle (fwd + all grads) — h=4/hd=32 routes to pack=4."""
    q, k, v = qkv(t=256, seed=13)
    got_btd, got_bh = _both_layouts(q, k, v, monkeypatch)
    want = attn_ops.causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got_btd), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_bh), np.asarray(want),
                               rtol=1e-5, atol=1e-5)

    def loss(fn, q, k, v):
        return jnp.sum(jnp.square(fn(q, k, v)))

    monkeypatch.setenv("FLASH_LAYOUT", "auto")
    g_got = jax.grad(lambda *a: loss(flash.causal_attention, *a),
                     argnums=(0, 1, 2))(q, k, v)
    g_want = jax.grad(lambda *a: loss(attn_ops.causal_attention, *a),
                      argnums=(0, 1, 2))(q, k, v)
    for want_g, got_g, name in zip(g_want, g_got, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got_g), np.asarray(want_g), rtol=2e-4, atol=2e-4,
            err_msg=f"d{name} mismatch (btd)",
        )


def test_btd_pack1_head_dim_128(monkeypatch):
    """hd=128 -> pack=1 (llama head dim): single-head cells, no pairing."""
    q, k, v = qkv(t=128, h=2, hd=128, seed=17)
    got_btd, got_bh = _both_layouts(q, k, v, monkeypatch)
    want = attn_ops.causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got_btd), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_btd), np.asarray(got_bh),
                               rtol=1e-6, atol=1e-6)


def test_btd_window_softcap_grad_parity(monkeypatch):
    """Sliding window + logit softcap compose on the native-layout path,
    forward and backward (the mistral/gemma kernel features)."""
    q, k, v = qkv(t=256, seed=19)
    kw = dict(window=40, logit_softcap=30.0)
    got_btd, got_bh = _both_layouts(q, k, v, monkeypatch, **kw)
    want = attn_ops.causal_attention(q, k, v, **kw)
    np.testing.assert_allclose(np.asarray(got_btd), np.asarray(want),
                               rtol=1e-5, atol=1e-5)

    def loss(fn, q, k, v):
        return jnp.sum(jnp.square(fn(q, k, v, **kw)))

    monkeypatch.setenv("FLASH_LAYOUT", "auto")
    g_got = jax.grad(lambda *a: loss(flash.causal_attention, *a),
                     argnums=(0, 1, 2))(q, k, v)
    g_want = jax.grad(lambda *a: loss(attn_ops.causal_attention, *a),
                      argnums=(0, 1, 2))(q, k, v)
    for want_g, got_g, name in zip(g_want, g_got, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got_g), np.asarray(want_g), rtol=2e-4, atol=2e-4,
            err_msg=f"d{name} mismatch (btd window+softcap)",
        )


def test_btd_gqa_grad_parity(monkeypatch):
    """GQA routes through repeat_kv OUTSIDE the custom vjp: autodiff must
    sum dk/dv over the query-head group exactly as the oracle does."""
    q, k, v = qkv(t=128, h=4, kv=2, seed=23)

    def loss(fn, q, k, v):
        return jnp.sum(jnp.square(fn(q, k, v)))

    monkeypatch.setenv("FLASH_LAYOUT", "auto")
    g_got = jax.grad(lambda *a: loss(flash.causal_attention, *a),
                     argnums=(0, 1, 2))(q, k, v)
    g_want = jax.grad(lambda *a: loss(attn_ops.causal_attention, *a),
                      argnums=(0, 1, 2))(q, k, v)
    for want_g, got_g, name in zip(g_want, g_got, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got_g), np.asarray(want_g), rtol=2e-4, atol=2e-4,
            err_msg=f"d{name} mismatch (btd gqa)",
        )


def test_btd_fused_backward_parity(monkeypatch):
    """The fused dq+dk+dv kernel (FLASH_FUSED_BWD=1, opt-in until
    chip-validated) must match the split kernels AND the oracle — plain
    causal, then window+softcap (every masked-cell branch).

    FLASH_BLOCK=128 forces nb=2 at t=256: without it the whole fused
    machinery under test — the cross-kj dq slab accumulation, the parked
    dq out-spec flush, and the full-cell qi>kj branch — never runs (a
    single-block grid has one diagonal cell and nothing to accumulate
    across)."""
    monkeypatch.setenv("FLASH_LAYOUT", "auto")
    monkeypatch.setenv("FLASH_BLOCK", "128")

    for kw in ({}, dict(window=40, logit_softcap=30.0)):
        q, k, v = qkv(t=256, seed=29)

        def loss(fn, q, k, v):
            return jnp.sum(jnp.square(fn(q, k, v, **kw)))

        monkeypatch.setenv("FLASH_FUSED_BWD", "1")
        g_fused = jax.grad(lambda *a: loss(flash.causal_attention, *a),
                           argnums=(0, 1, 2))(q, k, v)
        monkeypatch.setenv("FLASH_FUSED_BWD", "0")
        g_split = jax.grad(lambda *a: loss(flash.causal_attention, *a),
                           argnums=(0, 1, 2))(q, k, v)
        g_want = jax.grad(lambda *a: loss(attn_ops.causal_attention, *a),
                          argnums=(0, 1, 2))(q, k, v)
        for want, fused, split, name in zip(g_want, g_fused, g_split, "qkv"):
            np.testing.assert_allclose(
                np.asarray(fused), np.asarray(want), rtol=2e-4, atol=2e-4,
                err_msg=f"d{name} fused-vs-oracle mismatch ({kw})",
            )
            np.testing.assert_allclose(
                np.asarray(fused), np.asarray(split), rtol=1e-6, atol=1e-6,
                err_msg=f"d{name} fused-vs-split mismatch ({kw})",
            )


def test_btd_odd_head_count_pads(monkeypatch):
    """Odd H (gpt2-xl's 25 heads) takes the btd path via zero-head
    padding: forward and all grads must still match the oracle."""
    monkeypatch.setenv("FLASH_LAYOUT", "auto")
    q, k, v = qkv(t=128, h=3, hd=32, seed=31)

    def loss(fn, q, k, v):
        return jnp.sum(jnp.square(fn(q, k, v)))

    got = flash.causal_attention(q, k, v)
    want = attn_ops.causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    g_got = jax.grad(lambda *a: loss(flash.causal_attention, *a),
                     argnums=(0, 1, 2))(q, k, v)
    g_want = jax.grad(lambda *a: loss(attn_ops.causal_attention, *a),
                      argnums=(0, 1, 2))(q, k, v)
    for want_g, got_g, name in zip(g_want, g_got, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got_g), np.asarray(want_g), rtol=2e-4, atol=2e-4,
            err_msg=f"d{name} mismatch (odd-H pad)",
        )
