"""Flash-attention kernel parity vs the einsum oracle (fwd + grads), run in
Pallas interpret mode on CPU (SURVEY §7 hard-part #4: correctness vs the
oracle first, performance on hardware second)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mingpt_distributed_tpu.config import GPTConfig
from mingpt_distributed_tpu.models import gpt
from mingpt_distributed_tpu.ops import attention as attn_ops
from mingpt_distributed_tpu.ops import flash_attention as flash


def qkv(b=2, t=128, h=4, kv=None, hd=32, seed=0, dtype=jnp.float32):
    kv = kv or h
    ks = jax.random.split(jax.random.key(seed), 3)
    q = jax.random.normal(ks[0], (b, t, h, hd), dtype)
    k = jax.random.normal(ks[1], (b, t, kv, hd), dtype)
    v = jax.random.normal(ks[2], (b, t, kv, hd), dtype)
    return q, k, v


def test_forward_parity():
    q, k, v = qkv()
    want = attn_ops.causal_attention(q, k, v)
    got = flash.causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_forward_parity_gqa():
    q, k, v = qkv(h=4, kv=2)
    want = attn_ops.causal_attention(q, k, v)
    got = flash.causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_forward_parity_multiblock():
    # T=256 -> block 128 x 2: exercises the streaming-softmax accumulation
    q, k, v = qkv(t=256, seed=3)
    want = attn_ops.causal_attention(q, k, v)
    got = flash.causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_gradient_parity():
    q, k, v = qkv(t=128, seed=5)

    def loss(fn, q, k, v):
        return jnp.sum(jnp.square(fn(q, k, v)))

    g_want = jax.grad(lambda *a: loss(attn_ops.causal_attention, *a),
                      argnums=(0, 1, 2))(q, k, v)
    g_got = jax.grad(lambda *a: loss(flash.causal_attention, *a),
                     argnums=(0, 1, 2))(q, k, v)
    for want, got, name in zip(g_want, g_got, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4,
            err_msg=f"d{name} mismatch",
        )


def test_gradient_parity_gqa_multiblock():
    q, k, v = qkv(t=256, h=4, kv=1, seed=7)

    def loss(fn, q, k, v):
        return jnp.sum(jnp.square(fn(q, k, v)))

    g_want = jax.grad(lambda *a: loss(attn_ops.causal_attention, *a),
                      argnums=(0, 1, 2))(q, k, v)
    g_got = jax.grad(lambda *a: loss(flash.causal_attention, *a),
                     argnums=(0, 1, 2))(q, k, v)
    for want, got, name in zip(g_want, g_got, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4,
            err_msg=f"d{name} mismatch",
        )


def test_gradient_parity_long_sequence():
    """T=1024 -> 512-blocks streamed via the grid (the FA2 re-tiling): the
    per-cell VMEM footprint must not depend on T, and the scratch-carried
    online softmax must stay exact across many k blocks."""
    q, k, v = qkv(b=1, t=1024, h=2, seed=11)

    def loss(fn, q, k, v):
        return jnp.sum(jnp.square(fn(q, k, v)))

    want = attn_ops.causal_attention(q, k, v)
    got = flash.causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    g_want = jax.grad(lambda *a: loss(attn_ops.causal_attention, *a),
                      argnums=(0, 1, 2))(q, k, v)
    g_got = jax.grad(lambda *a: loss(flash.causal_attention, *a),
                     argnums=(0, 1, 2))(q, k, v)
    for want, got, name in zip(g_want, g_got, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=5e-4, atol=5e-4,
            err_msg=f"d{name} mismatch",
        )


def test_fallback_paths_route_to_oracle():
    # dropout active -> einsum fallback (still correct, just not flash)
    q, k, v = qkv(t=64)
    out = flash.causal_attention(
        q, k, v, attn_pdrop=0.5, dropout_key=jax.random.key(0),
        deterministic=False,
    )
    assert out.shape == q.shape
    # decode-style (q_len 1 vs cache 64) -> fallback with kv_offset
    out = flash.causal_attention(q[:, :1], k, v, kv_offset=63)
    assert out.shape == (2, 1, 4, 32)
    # odd T -> fallback
    out = flash.causal_attention(q[:, :37], k[:, :37], v[:, :37])
    want = attn_ops.causal_attention(q[:, :37], k[:, :37], v[:, :37])
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_model_forward_with_flash_matches_einsum():
    """End-to-end: gpt_config.attention=flash must reproduce einsum logits."""
    base = dict(
        n_layer=2, n_head=2, n_embd=32, vocab_size=50, block_size=128,
        embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0, dtype="float32",
    )
    cfg_e = GPTConfig.make(**base, attention="einsum")
    cfg_f = GPTConfig.make(**base, attention="flash")
    params = gpt.init(jax.random.key(0), cfg_e)
    tokens = jax.random.randint(jax.random.key(1), (2, 128), 0, 50)
    le, _ = gpt.forward(params, tokens, cfg_e, targets=tokens)
    lf, _ = gpt.forward(params, tokens, cfg_f, targets=tokens)
    np.testing.assert_allclose(np.asarray(lf), np.asarray(le),
                               rtol=2e-4, atol=2e-4)
