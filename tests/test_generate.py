"""Generation tests: KV-cached decode must agree with the dense forward
(the einsum oracle), plus determinism / sampling / llama-mode coverage."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mingpt_distributed_tpu.config import GPTConfig
from mingpt_distributed_tpu.models import generate as gen
from mingpt_distributed_tpu.models import gpt


def cfg_and_params(**kw):
    base = dict(
        n_layer=2, n_head=2, n_embd=32, vocab_size=50, block_size=32,
        embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0, dtype="float32",
    )
    base.update(kw)
    cfg = GPTConfig.make(**base)
    return cfg, gpt.init(jax.random.key(0), cfg)


def dense_greedy(params, cfg, idx, n):
    """Reference-style loop: full re-forward each step, argmax (the
    crop-and-append semantics of model.py:322-356, as an oracle)."""
    idx = jnp.asarray(idx)
    for _ in range(n):
        idx_cond = idx[:, -cfg.block_size:]
        logits, _ = gpt.forward(params, idx_cond, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        idx = jnp.concatenate([idx, nxt[:, None]], axis=1)
    return idx


def test_cached_greedy_matches_dense_oracle():
    cfg, params = cfg_and_params()
    prompt = jax.random.randint(jax.random.key(1), (2, 5), 0, 50)
    want = dense_greedy(params, cfg, prompt, 10)
    got = gen.generate(params, cfg, prompt, 10)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


def test_cached_greedy_matches_dense_oracle_llama():
    cfg, params = cfg_and_params(
        rope=True, swiglu=True, rmsnorm=True, n_kv_head=1, tie_weights=True
    )
    prompt = jax.random.randint(jax.random.key(1), (2, 5), 0, 50)
    want = dense_greedy(params, cfg, prompt, 8)
    got = gen.generate(params, cfg, prompt, 8)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


def test_sampling_deterministic_given_key():
    cfg, params = cfg_and_params()
    prompt = jnp.zeros((1, 3), dtype=jnp.int32)
    a = gen.generate(params, cfg, prompt, 12, do_sample=True, temperature=0.8,
                     top_k=10, rng=jax.random.key(42))
    b = gen.generate(params, cfg, prompt, 12, do_sample=True, temperature=0.8,
                     top_k=10, rng=jax.random.key(42))
    c = gen.generate(params, cfg, prompt, 12, do_sample=True, temperature=0.8,
                     top_k=10, rng=jax.random.key(43))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_top_k_restricts_support():
    cfg, params = cfg_and_params()
    prompt = jnp.zeros((1, 3), dtype=jnp.int32)
    # top_k=1 sampling == greedy
    sampled = gen.generate(params, cfg, prompt, 8, do_sample=True, top_k=1,
                           rng=jax.random.key(0))
    greedy = gen.generate(params, cfg, prompt, 8)
    np.testing.assert_array_equal(np.asarray(sampled), np.asarray(greedy))
    # top_k larger than vocab is clamped, not an error
    gen.generate(params, cfg, prompt, 2, do_sample=True, top_k=10_000,
                 rng=jax.random.key(0))


def test_generation_crosses_context_window():
    """Unbounded generation (reference model.py:336-337): max_new_tokens may
    exceed the room left in — or the entirety of — the context window; every
    token past the boundary must match the crop-and-append dense oracle."""
    cfg, params = cfg_and_params(block_size=16)
    prompt = jax.random.randint(jax.random.key(1), (2, 10), 0, 50)
    n = 20  # 10 + 20 > 16: crosses the boundary mid-generation
    want = dense_greedy(params, cfg, prompt, n)
    got = gen.generate(params, cfg, prompt, n)
    assert got.shape == (2, 30)  # full prompt stays in the output
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


def test_generation_exceeds_block_size_entirely():
    """max_new_tokens > block_size: the window slides the whole way."""
    cfg, params = cfg_and_params(block_size=16)
    prompt = jax.random.randint(jax.random.key(2), (1, 3), 0, 50)
    n = 24  # > block_size
    want = dense_greedy(params, cfg, prompt, n)
    got = gen.generate(params, cfg, prompt, n)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


def test_long_prompt_cropped_but_preserved_in_output():
    cfg, params = cfg_and_params(block_size=16)
    long_prompt = jax.random.randint(jax.random.key(1), (1, 40), 0, 50)
    want = dense_greedy(params, cfg, long_prompt, 4)
    out = gen.generate(params, cfg, long_prompt, 4)
    assert out.shape == (1, 44)  # reference returns prompt + new tokens
    np.testing.assert_array_equal(np.asarray(want), np.asarray(out))


def test_sliding_window_sampling_in_bounds():
    """Sampled decode across the boundary stays in-vocab and deterministic
    under a fixed key (the sliding path threads the same PRNG contract)."""
    cfg, params = cfg_and_params(block_size=16)
    prompt = jnp.zeros((1, 3), dtype=jnp.int32)
    a = gen.generate(params, cfg, prompt, 20, do_sample=True, temperature=0.9,
                     top_k=5, rng=jax.random.key(7))
    b = gen.generate(params, cfg, prompt, 20, do_sample=True, temperature=0.9,
                     top_k=5, rng=jax.random.key(7))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(np.asarray(a).max()) < 50 and int(np.asarray(a).min()) >= 0


def test_1d_prompt_and_single_token():
    cfg, params = cfg_and_params()
    out = gen.generate(params, cfg, jnp.array([1, 2, 3]), 1)
    assert out.shape == (1, 4)


def test_top_p_restricts_support_to_nucleus():
    """VERDICT r2 missing #4: top_p is now reachable through generate().
    Distribution check on _select_next: with a known logit vector, nucleus
    filtering must only ever sample tokens inside the top-p mass."""
    # probs ~ [0.6, 0.3, 0.06, 0.04]: nucleus at top_p=0.7 is {0, 1}
    logits = jnp.log(jnp.asarray([[0.6, 0.3, 0.06, 0.04]]))
    seen = set()
    for i in range(200):
        tok = gen._select_next(
            logits, jax.random.key(i), temperature=1.0, do_sample=True,
            top_k=None, top_p=0.7,
        )
        seen.add(int(tok[0]))
    assert seen <= {0, 1}, seen
    assert seen == {0, 1}, "both nucleus tokens should appear in 200 draws"

    # tiny/zero top_p degenerates to greedy (top token always survives,
    # never an all-masked distribution collapsing to token id 0)
    for tp in (1e-6, 0.0):
        for i in range(20):
            tok = gen._select_next(
                logits, jax.random.key(i), temperature=1.0, do_sample=True,
                top_k=None, top_p=tp,
            )
            assert int(tok[0]) == 0

    # end-to-end: top_p plumbed through generate() — tiny top_p == greedy
    cfg, params = cfg_and_params()
    prompt = jnp.zeros((1, 3), dtype=jnp.int32)
    sampled = gen.generate(params, cfg, prompt, 8, do_sample=True,
                           top_p=1e-6, rng=jax.random.key(0))
    greedy = gen.generate(params, cfg, prompt, 8)
    np.testing.assert_array_equal(np.asarray(sampled), np.asarray(greedy))
    # and through the sliding-window path (prompt+new > block_size)
    long_prompt = jnp.zeros((1, 30), dtype=jnp.int32)
    out = gen.generate(params, cfg, long_prompt, 8, do_sample=True,
                       top_p=0.9, rng=jax.random.key(1))
    assert out.shape == (1, 38)
    assert (np.asarray(out) >= 0).all() and (np.asarray(out) < 50).all()
