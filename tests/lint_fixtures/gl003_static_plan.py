"""GL003 fixture — the static-plan idiom (parallel/zero.py, ISSUE 9).

The ZeRO update view branches per leaf on FROZEN dataclass fields
(``mode``/``pad``) of a plan built before tracing: those are fixed
python values, so the jitted program contains no traced branching and
the branch is clean. Positives: the same-shaped branch taken on a
traced value instead.
"""
from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class _LeafPlan:
    mode: str
    pad: int


_PLAN = _LeafPlan(mode="flat", pad=3)


@jax.jit
def pads_by_static_plan(x):
    flat = jnp.reshape(x, (-1,))
    if _PLAN.pad:  # clean: plan fields are fixed python ints at trace time
        flat = jnp.pad(flat, (0, _PLAN.pad))
    return flat


@jax.jit
def branches_on_traced_leaf(x):
    if x > 0:  # expect: GL003
        return x
    return -x


@jax.jit
def pad_amount_from_tracer(x):
    pad = x + 0  # a traced value standing in for a miscomputed pad
    if pad:  # graftlint: disable=GL003
        x = x + 1
    return x
