"""GL008/GL009 fixtures — metric naming and registry coherence.

Positives: an off-convention family name; the same family registered
as two instrument types; an orphan mingpt_* literal.
Suppressed: one of each, inline disable.
Negatives: the get-or-create idiom (same name, same type, twice), an
f-string family with a conventional prefix, and a literal that matches
a registered family.
"""


class _Reg:
    """Stand-in with the MetricsRegistry registration surface."""

    def counter(self, name, help=""):
        return name

    def gauge(self, name, help=""):
        return name


REG = _Reg()
shard = 0

BAD_NAME = REG.counter("serving_rejected_total")  # expect: GL008
BAD_SUPPRESSED = REG.counter("tokens")  # graftlint: disable=GL008
OK_NAME = REG.counter("mingpt_fixture_ok_total")

FIRST = REG.counter("mingpt_fixture_conflict_total")
SECOND = REG.gauge("mingpt_fixture_conflict_total")  # expect: GL009
SUP_FIRST = REG.counter("mingpt_fixture_dup_total")
SUP_SECOND = REG.gauge("mingpt_fixture_dup_total")  # graftlint: disable=GL009

SHARED_A = REG.counter("mingpt_fixture_shared_total")
SHARED_B = REG.counter("mingpt_fixture_shared_total")  # clean: get-or-create

PER_SHARD = REG.gauge(f"mingpt_fixture_shard{shard}_depth")  # clean prefix

ORPHAN = "mingpt_fixture_missing_total"  # expect: GL009
ORPHAN_SUPPRESSED = "mingpt_fixture_ghost_total"  # graftlint: disable=GL009
KNOWN = "mingpt_fixture_ok_total"  # clean: matches a registered family
