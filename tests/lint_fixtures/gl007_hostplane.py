"""GL007 fixtures — wall-clock shapes in the cross-host hostplane.

Positives: a wall read deciding a heartbeat deadline; a wall sleep
pacing a token-bucket transfer; ``time.monotonic()`` driving a
connect-retry backoff.
Suppressed: one wall read stamping a transfer report, inline disable.
Negatives: the hostplane-approved shapes — the peer-state ladder and
the bucket refill both read the injected fleet clock, pacing *advances*
that clock instead of sleeping, and the bounded socket retry takes an
injectable sleep as a default argument (a reference, never a call —
the ``RetryPolicy.sleep`` idiom again).
"""
import time


def heartbeat_deadline_bad(last_contact, suspect_after_s):
    # a wall read deciding suspect/quarantined/dead makes the ladder
    # unreplayable — one slow test machine flaps a healthy peer
    return time.monotonic() - last_contact >= suspect_after_s  # expect: GL007


def paced_send_bad(nbytes, bytes_per_s):
    time.sleep(nbytes / bytes_per_s)  # expect: GL007


def connect_backoff_bad(attempt, backoff_s):
    deadline = time.time() + backoff_s * (2 ** attempt)  # expect: GL007
    return deadline


def transfer_report_suppressed():
    return time.perf_counter()  # graftlint: disable=GL007


def ladder_rung(clock, last_contact, suspect_after_s):
    # clean: elapsed silence is measured on the injected fleet clock,
    # so two partition drills degrade a peer on the same virtual tick
    return clock.now() - last_contact >= suspect_after_s


def token_bucket_refill(clock, tokens, last_refill, bytes_per_s, burst):
    # clean: the bucket refills from the same injected clock it waits
    # on — bandwidth budgets are virtual-seconds math, not wall time
    return min(burst, tokens + (clock.now() - last_refill) * bytes_per_s)


def paced_wait(clock, deficit_bytes, bytes_per_s):
    # clean: pacing ADVANCES the injected clock rather than sleeping;
    # on a virtual clock the transfer takes exactly bytes/rate seconds
    clock.advance(deficit_bytes / bytes_per_s)
    return clock.now()


def bounded_connect_retry(sleep=time.sleep):  # clean: reference, not call
    return sleep
