"""GL007 fixtures — wall-clock calls in clock-disciplined paths.

Positives: time.monotonic() in a scheduling decision; a
``from time import``-aliased sleep call.
Suppressed: one perf_counter call, inline disable.
Negatives: the three allowlisted shapes — telemetry-timestamp binding,
a ``*Clock`` class body, and an injectable default-arg *reference*.
"""
import time
from time import sleep as wall_sleep


def deadline_bad():
    return time.monotonic() + 1.0  # expect: GL007


def backoff_bad(delay_s):
    wall_sleep(delay_s)  # expect: GL007


def probe_suppressed():
    return time.perf_counter()  # graftlint: disable=GL007


def stamp_record(value):
    ts = time.time()  # clean: epoch stamp on an exported record is data
    return {"ts": ts, "value": value}


def injectable(sleep=time.sleep):  # clean: a reference, not a call
    return sleep


class FakeClock:
    def now(self):
        return time.perf_counter()  # clean: *Clock IS the abstraction
