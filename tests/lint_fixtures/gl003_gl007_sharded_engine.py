"""GL003/GL007 fixtures — the hazards mesh-aware serving must avoid.

The sharded DecodeEngine (serving/engine.py) makes the mesh part of
each program family's COMPILE identity, not a traced input: the pool's
``NamedSharding`` rides into the jit wrapper as a ``functools.partial``
bound kwarg (static, exactly like ``cfg``), and the ``None``
single-device branch lives in an un-jitted pin helper the traced body
merely calls. Passing the sharding per call and branching on it inside
the jitted body would specialise per value — the retrace the
one-executable-per-family guarantee forbids. And any wait on a shard
transfer must read the injected clock, never the wall, or the chaos
tests stop being deterministic.

Positives: a jitted body that takes the sharding as a call argument
and branches on it; a traced live-lane branch; a wall-clock transfer
deadline. Suppressed: one traced retry-while, inline disable.
Negatives: the partial-bound sharding constant; the un-jitted pin
helper's None branch; a branch on ``.sharding`` (trace-static
attribute, like ``.shape``); the injected-clock deadline.
"""
import functools
import time

import jax

POOL_SHARDING = object()  # stands in for the pool's NamedSharding


def _pin(cache, kv_sharding):
    if kv_sharding is None:  # clean: un-jitted helper — host branch
        return cache
    return {k: jax.lax.with_sharding_constraint(v, kv_sharding)
            for k, v in cache.items()}


def _decode_like(params, cache, kv_sharding=None):
    new = {k: v + params for k, v in cache.items()}
    return _pin(new, kv_sharding)


# clean: the mesh-in-compile-key idiom — the sharding is a
# partial-bound constant of the wrapper, so the wrapper IS the mesh
# decision and the family keeps exactly one executable per engine
decode_sharded = jax.jit(
    functools.partial(_decode_like, kv_sharding=POOL_SHARDING))


@jax.jit
def decode_takes_sharding_per_call(cache, kv_sharding):
    if kv_sharding is None:  # expect: GL003
        return cache
    return {k: jax.lax.with_sharding_constraint(v, kv_sharding)
            for k, v in cache.items()}


@jax.jit
def prefill_branches_on_live_lanes(cache, n_live):
    if n_live > 0:  # expect: GL003
        return cache
    return {k: v * 0 for k, v in cache.items()}


@jax.jit
def install_retries_traced(cache, tries):
    while tries < 3:  # graftlint: disable=GL003
        tries = tries + 1
    return cache


@jax.jit
def repin_reads_static_sharding(cache, fallback):
    # clean: ``.sharding`` is concrete at trace time (STATIC_ATTRS,
    # like ``.shape``) — how the engine's pin helper stays branch-free
    if cache["k"].sharding is None:
        return fallback
    return cache


def transfer_deadline_bad(deadline):
    return time.perf_counter() >= deadline  # expect: GL007


def transfer_deadline_injected(clock, deadline):
    return clock() >= deadline  # clean: the scheduler's injected clock
