"""GL003/GL007 fixtures — the hazards the quantized KV path must avoid.

serving/quant.py makes the quantization descriptor part of each program
family's COMPILE identity (ISSUE 18): the frozen ``KVQuant`` rides into
the jit wrapper as a ``functools.partial`` bound kwarg — static,
exactly like ``cfg`` and the pool sharding — and the fp32-vs-quantized
decision therefore happens once per wrapper, never inside a trace.
Inside the traced body the only data-dependent quantization decision
(the all-zero channel whose scale must stay exact zero) is a masked
``jnp.where`` select, never a Python branch: branching on a traced
``amax`` or on a per-call descriptor would specialise per value and
break the one-executable-per-family guarantee. And the quant-error
gauge sampling must read the injected clock, never the wall, or the
virtual-clock chaos tests stop being deterministic.

Positives: a jitted body that takes the descriptor as a call argument
and branches on it; a traced branch on the amax value. Suppressed: one
traced clip-retry loop, inline disable. Negatives: the partial-bound
descriptor constant; the un-jitted host-side resolve; the masked
zero-channel select; the injected-clock gauge sampler.
"""
import functools
import time

import jax
import jax.numpy as jnp

KV_QUANT = object()  # stands in for the frozen KVQuant descriptor


def _dequant_lane(lane, kv_quant):
    if kv_quant is None:  # clean: un-jitted helper — host branch
        return lane
    return {k: v * 2.0 for k, v in lane.items()}


def _decode_like(params, lane, kv_quant=None):
    out = {k: v + params for k, v in _dequant_lane(lane, kv_quant).items()}
    return out


# clean: the dtype-in-compile-key idiom — the descriptor is a
# partial-bound constant of the wrapper, so the wrapper IS the dtype
# decision and each family keeps one executable per engine
decode_quantized = jax.jit(
    functools.partial(_decode_like, kv_quant=KV_QUANT))


@jax.jit
def decode_takes_quant_per_call(lane, kv_quant):
    if kv_quant is None:  # expect: GL003
        return lane
    return {k: v * 2.0 for k, v in lane.items()}


@jax.jit
def quantize_branches_on_amax(x, qmax):
    amax = jnp.max(jnp.abs(x))
    if amax > 0:  # expect: GL003
        return x / (amax / qmax)
    return x


@jax.jit
def quantize_clips_retry_traced(x, tries):
    while tries < 3:  # graftlint: disable=GL003
        tries = tries + 1
    return x


@jax.jit
def quantize_masks_zero_channels(x, qmax):
    # clean: the zero-channel decision as a masked select — the shape
    # quant._pow2_scale uses, branch-free under trace
    amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / qmax, 0.0)
    return x * scale


def sample_quant_gauge_bad(gauge, err):
    gauge((time.time(), err))  # expect: GL007
    return err


def sample_quant_gauge_injected(gauge, clock, err):
    gauge((clock(), err))  # clean: the scheduler's injected clock
    return err
