"""GL007 fixtures — wall-clock temptations in control-plane-shaped code.

The SLO autoscaler's guarantee is that an autoscaled sweep is
byte-replayable: every governor decision is a function of
ControlSnapshot fields sampled off the router's injected clock, and
the ``mingpt-control/1`` log stamps virtual ``now`` values. These
fixtures are the shapes that would quietly break it.

Positives: a governor that reads ``time.monotonic()`` to decide
whether the cooldown has expired; a scale-up actuator that really
sleeps while waiting for the spawned replica to warm.
Suppressed: one wall-clock tick-duration probe, inline disable.
Negatives: a telemetry ``*_ts`` stamp on an exported decision record,
an injectable clock default passed by reference, and a ``*Clock``
class body.
"""
import time
from time import sleep


def cooldown_expired_bad(cooldown_until):
    return time.monotonic() >= cooldown_until  # expect: GL007


def scale_up_bad(supervisor):
    rep = supervisor.spawn_replica()
    sleep(0.05)  # expect: GL007
    return rep


def tick_wall_seconds_suppressed():
    return time.perf_counter()  # graftlint: disable=GL007


def export_decision(decision):
    decision_ts = time.time()  # clean: epoch stamp on an exported record
    decision["decision_ts"] = decision_ts
    return decision


def govern(clock=time.monotonic):  # clean: injectable reference, not a call
    return clock


class GovernorClock:
    """The injected clock a governor should be handed instead."""

    def __init__(self):
        self._now = 0.0

    def now(self):
        return self._now or time.perf_counter()  # clean: *Clock body
