"""GL001 fixtures — donated-restore.

Positive: a donating step fed state straight off a restore.
Suppressed: same shape, inline disable.
Negative: the trainer's laundering idiom (compiled undonated copy).

NOTE: the ``# expect: GLxxx`` trailers are read by
tests/test_graftlint.py — every marked line must produce exactly that
active finding, and no unmarked line may produce any.
"""
import jax
import jax.numpy as jnp


class BadTrainer:
    def __init__(self, step_fn, path):
        self._step = jax.jit(step_fn, donate_argnums=(0,))
        self.state = restore_snapshot(path)

    def step(self, batch):
        self.state, m = self._step(self.state, batch)  # expect: GL001
        return m


class SuppressedTrainer:
    def __init__(self, step_fn, path):
        self._step = jax.jit(step_fn, donate_argnums=(0,))
        self.state = restore_snapshot(path)

    def step(self, batch):
        self.state, m = self._step(self.state, batch)  # graftlint: disable=GL001
        return m


class GoodTrainer:
    def __init__(self, step_fn, path):
        self._step = jax.jit(step_fn, donate_argnums=(0,))
        placed = restore_snapshot(path)
        # the laundering idiom: one compiled, undonated copy makes the
        # buffers executable-owned before the donating step sees them
        self.state = jax.jit(lambda s: jax.tree.map(jnp.copy, s))(placed)

    def step(self, batch):
        self.state, m = self._step(self.state, batch)
        return m
