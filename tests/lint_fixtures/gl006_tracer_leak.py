"""GL006 fixtures — traced values escaping a jitted function.

Positives: global declaration, self-attribute store, module-level
container store — all inside jitted code.
Suppressed: one container store, inline disable.
Negative: a store into a function-local container (explicit carry).
"""
import jax

_CACHE = {}


@jax.jit
def leak_global(x):
    global _LAST  # expect: GL006
    _LAST = x
    return x + 1


class LeakyModule:
    @jax.jit
    def forward(self, x):
        self.peek = x + 1  # expect: GL006
        return x * 2


@jax.jit
def leak_container(x):
    _CACHE["x"] = x * 2  # expect: GL006
    return x


@jax.jit
def leak_suppressed(x):
    _CACHE["y"] = x  # graftlint: disable=GL006
    return x


@jax.jit
def clean_carry(x):
    acc = {}
    acc["x"] = x  # clean: local container, dies with the trace
    return acc["x"] + 1
