"""GL002/GL003 fixtures — traced coercion and traced branching.

Positives: f-string/str() on a traced value; if/while on a traced test.
Suppressed: one of each, inline disable.
Negatives: branching/formatting on static args and on ``.shape``
products — both trace-time-concrete by design.
"""
from functools import partial

import jax
import jax.numpy as jnp


@jax.jit
def coerces_fstring(x):
    label = f"value={x}"  # expect: GL002
    return x + 1, label


@jax.jit
def coerces_str(x):
    return str(x)  # expect: GL002


@jax.jit
def coerces_suppressed(x):
    return str(x)  # graftlint: disable=GL002


@jax.jit
def shape_is_static(x):
    b, t = x.shape
    tag = f"batch={b}"  # clean: .shape products are concrete under trace
    del tag
    return x.reshape(b * t)


@jax.jit
def branches_if(x):
    if x > 0:  # expect: GL003
        return x
    return -x


@jax.jit
def branches_while(x):
    while x < 0:  # graftlint: disable=GL003
        x = x + 1
    return x


@partial(jax.jit, static_argnames=("flag",))
def static_branch(x, flag):
    if flag:  # clean: flag is a static arg — retracing here is the point
        return x * 2
    return jnp.where(x > 0, x, -x)  # clean: the traced-branch idiom
