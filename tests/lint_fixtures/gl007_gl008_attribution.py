"""GL007/GL008 fixtures — wall-clock and naming temptations in
attribution-shaped code.

The attribution ledger's guarantee (ISSUE 13) is that two serving runs
on the same VirtualClock dump byte-identical ``mingpt-attrib/1``
reports — which holds only while every compile/device timestamp is
read from the injected clock, never the wall. These are the shapes
that would quietly break it, plus the ledger's gauge-family naming
contract.

Positives: timing an AOT compile with ``time.perf_counter()``;
sampling a device interval through an imported ``perf_counter``
alias; an off-convention ledger gauge name.
Suppressed: one wall-clock headroom probe and one bad name, inline
disable.
Negatives: the injected-clock compile timer, a ``wall_ts`` report
stamp, an injectable clock default, and the ledger's real
``mingpt_attrib_*`` registrations.
"""
import time
from time import perf_counter


class _Reg:
    """Stand-in with the MetricsRegistry registration surface."""

    def counter(self, name, help="", labels=()):
        return name

    def gauge(self, name, help="", labels=()):
        return name


REG = _Reg()


def timed_compile_bad(jit_fn, args):
    t0 = time.perf_counter()  # expect: GL007
    compiled = jit_fn.lower(*args).compile()
    return compiled, time.perf_counter() - t0  # expect: GL007


def observe_call_bad(ledger, family, started):
    ledger.observe_call(family, perf_counter() - started)  # expect: GL007


def hbm_probe_wall_suppressed():
    return time.monotonic()  # graftlint: disable=GL007


def timed_compile(jit_fn, args, clock):
    t0 = clock()  # clean: injected clock
    compiled = jit_fn.lower(*args).compile()
    return compiled, clock() - t0


def stamp_report(report):
    wall_ts = time.time()  # clean: epoch stamp on the exported report
    report["wall_ts"] = wall_ts
    return report


def make_ledger_clock(clock=time.perf_counter):  # clean: injectable ref
    return clock


FLOPS = REG.gauge("mingpt_attrib_flops", labels=("family", "variant"))
CALLS = REG.counter("mingpt_attrib_calls_total")  # clean: real family
HBM = REG.gauge("mingpt_attrib_hbm_bytes", labels=("owner",))
BAD_NAME = REG.gauge("attrib_mfu")  # expect: GL008
BAD_SUPPRESSED = REG.gauge("hbm_bytes")  # graftlint: disable=GL008
