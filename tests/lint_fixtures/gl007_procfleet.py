"""GL007 fixtures — wall-clock shapes at the procfleet RPC boundary.

Positives: a wall sleep in a respawn backoff; ``time.monotonic()``
deciding an RPC deadline.
Suppressed: one perf_counter read, inline disable.
Negatives: the procfleet-approved shapes — socket timeouts are
connection attributes (the OS enforces them; no ``time.*`` call), step
deadlines read an injected clock, slow-socket faults land as clock skew
rather than a sleep, and an injectable-sleep default argument is a
*reference*, not a call (the ``RetryPolicy.sleep`` idiom
``ProcessFaultInjector`` reuses).
"""
import socket
import time


def respawn_backoff_bad(used):
    time.sleep(0.05 * 2 ** used)  # expect: GL007


def rpc_deadline_bad(timeout_s):
    return time.monotonic() + timeout_s  # expect: GL007


def handshake_latency_suppressed():
    return time.perf_counter()  # graftlint: disable=GL007


def connect_with_timeout(host, port, timeout_s):
    # clean: a socket timeout is a connection attribute — nobody reads
    # or advances a clock here, the kernel does the timing
    conn = socket.create_connection((host, port), timeout=timeout_s)
    conn.settimeout(timeout_s * 4)
    return conn


def step_deadline(clock, timeout_s):
    return clock() + timeout_s  # clean: injected clock


def slow_socket_fault(clock, skew_s):
    clock.skew_s += skew_s  # clean: fault lands as skew, never a sleep
    return clock.skew_s


def injectable_rpc_retry(sleep=time.sleep):  # clean: reference, not call
    return sleep


def hang_deadline_bad(term_at):
    # a wall read deciding the SIGTERM->SIGKILL escalation would make
    # the ladder unreplayable on a virtual clock
    return time.time() - term_at  # expect: GL007


def standby_prewarm_bad():
    time.sleep(0.2)  # expect: GL007


def liveness_ladder(clock, since, deadline_s):
    # clean: the escalation deadline reads the supervisor's injected
    # clock, so the whole ladder replays deterministically
    return clock.now() - since >= deadline_s


def standby_spare_clock(fleet_clock):
    # clean: a spare's SkewedClock is seeded from the fleet clock it
    # will serve under, not from the wall
    return fleet_clock.now()
