"""GL007 fixtures — wall-clock temptations in traffic-lab-shaped code.

The traffic lab's whole guarantee is that a load sweep is replayable
from ``(seed, spec)``: arrival schedules are virtual-timestamp DATA and
the drive loop advances an injected clock. These fixtures are the
shapes that would quietly break it.

Positives: stamping an arrival with ``time.time()`` inside the
generator; an open-loop pacer that really sleeps between arrivals.
Suppressed: one wall-clock duration probe, inline disable.
Negatives: a ``*Clock`` class body, a telemetry ``*_ts`` stamp, and an
injectable clock default passed by reference.
"""
import time
from time import sleep


def emit_arrival_bad(rate):
    return {"at": time.time() + 1.0 / rate}  # expect: GL007


def pace_arrivals_bad(gaps):
    for gap in gaps:
        sleep(gap)  # expect: GL007


def sweep_wall_seconds_suppressed():
    return time.perf_counter()  # graftlint: disable=GL007


def stamp_report(report):
    report_ts = time.time()  # clean: epoch stamp on an exported record
    report["report_ts"] = report_ts
    return report


def drive(clock=time.monotonic):  # clean: injectable reference, not a call
    return clock


class SweepClock:
    """The virtual clock a runner should be handed instead."""

    def __init__(self):
        self._now = 0.0

    def now(self):
        return self._now or time.perf_counter()  # clean: *Clock body
