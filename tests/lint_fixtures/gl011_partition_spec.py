"""GL011 fixtures — PartitionSpec authored with trailing None.

Positives: trailing None on jax.sharding.PartitionSpec, on a P alias,
and the all-None spec.
Suppressed: one trailing-None spec, inline disable.
Negatives: interior None (load-bearing: positions a later axis), empty
spec, starred args (not statically a trailing None).
"""
from jax.sharding import PartitionSpec
from jax.sharding import PartitionSpec as P


def bad_specs(tp_axis):
    full = PartitionSpec("tp", None)  # expect: GL011
    alias = P(None, None, None, tp_axis, None)  # expect: GL011
    all_none = P(None)  # expect: GL011
    return full, alias, all_none


def suppressed_spec():
    # interop with an external checkpoint layout that spells head_dim
    return P("tp", None)  # graftlint: disable=GL011


def good_specs(dims):
    interior = P(None, "tp")  # clean: None positions tp on dim 1
    replicated = PartitionSpec()  # clean: the normalized empty spec
    dynamic = P(*dims)  # clean: not statically a trailing None
    return interior, replicated, dynamic
