"""GL003/GL007 fixtures — the hazards speculative decoding must avoid.

The verify/accept round (serving/speculative.py) is a host-side loop
around ONE jitted program: acceptance decisions happen on the host after
``device_get`` (concrete ints), never as Python branches on traced
values inside the verify body — per-round branching there would retrace
per acceptance pattern — and burst deadlines come from the scheduler's
injected clock, never the wall.

Positives: a traced accept-branch and a data-dependent early-out inside
jitted verify bodies; a wall-clock deadline read in the burst loop.
Suppressed: one traced while-loop, inline disable.
Negatives: host-side acceptance arithmetic on concrete ints; masked
rollback via ``jnp.where``; the injected-clock deadline check.
"""
import time

import jax
import jax.numpy as jnp


@jax.jit
def verify_branches_on_acceptance(proposals, greedy):
    if proposals[0] == greedy[0]:  # expect: GL003
        return proposals
    return greedy


@jax.jit
def verify_early_out(logits, threshold):
    best = jnp.max(logits)
    while best < threshold:  # graftlint: disable=GL003
        best = best + 1.0
    return best


@jax.jit
def rollback_is_masked_not_branched(rows, n_acc, stale):
    keep = jnp.arange(rows.shape[0]) < n_acc
    return jnp.where(keep, rows, stale)  # clean: the masked-rollback idiom


def host_accept_len(proposals, greedy):
    n_acc = 1  # clean: host ints after device_get — branching is free here
    while n_acc <= len(proposals) and proposals[n_acc - 1] == greedy[n_acc - 1]:
        n_acc += 1
    return n_acc


def burst_deadline_bad(deadline):
    return time.monotonic() >= deadline  # expect: GL007


def burst_deadline_injected(clock, deadline):
    return clock() >= deadline  # clean: the scheduler's injected clock
