"""GL004/GL005 fixtures — jit-in-loop and unhashable static args.

Positives: jax.jit built in a loop body; list literal at a static
position.
Suppressed: one of each, inline disable.
Negatives: hoisted construction; tuple at the static position.
"""
import jax


def run(x, dims):
    return x


step = jax.jit(run, static_argnames=("dims",))


def compile_per_batch(fns, batches):
    outs = []
    for fn, batch in zip(fns, batches):
        fresh = jax.jit(fn)  # expect: GL004
        outs.append(fresh(batch))
    return outs


def compile_per_batch_suppressed(fns, batches):
    outs = []
    for fn, batch in zip(fns, batches):
        fresh = jax.jit(fn)  # graftlint: disable=GL004
        outs.append(fresh(batch))
    return outs


def compile_once(fn, batches):
    hoisted = jax.jit(fn)  # clean: built once, reused across iterations
    return [hoisted(b) for b in batches]


def call_unhashable(x):
    return step(x, dims=[1, 2, 3])  # expect: GL005


def call_unhashable_suppressed(x):
    return step(x, dims=[1, 2])  # graftlint: disable=GL005


def call_hashable(x):
    return step(x, dims=(1, 2, 3))  # clean: tuples are hashable cache keys
