"""GL007 fixtures — the tracing/flight-recorder clock contract.

The request-trace recorder and flight recorder live in GL007 scope
(ISSUE 10): every span/event timestamp must be caller-supplied from an
injected clock, never read in-module — otherwise the chaos gate's
exact-duration trace assertions would depend on wall time.

Positives: a recorder reading the wall clock to stamp a span, and a
sleep-based flush backoff.
Suppressed: one monotonic read, inline disable.
Negatives: the manifest's ``wall_ts`` epoch anchor (ts-name binding)
and the caller-supplied ``now`` idiom itself.
"""
import time


class SpanLog:
    def __init__(self):
        self.spans = []
        self.wall_ts = 0.0

    def add_span_bad(self, name):
        self.spans.append({"name": name, "ts": time.monotonic()})  # expect: GL007

    def flush_bad(self):
        time.sleep(0.01)  # expect: GL007

    def probe_suppressed(self):
        return time.monotonic()  # graftlint: disable=GL007

    def stamp_manifest(self):
        # clean: the dump's epoch anchor is record data, not scheduling
        self.wall_ts = time.time()

    def add_span(self, name, now, dur_s):
        # clean: the caller injects the clock reading (the contract)
        self.spans.append({"name": name, "ts": now, "dur_s": dur_s})
