"""GL010 fixtures — bare print in library code.

Positives: print() and sys.stderr.write().
Suppressed: one print, inline disable.
Negative: routing through telemetry.spans.log_event.
"""
import sys


def report_bad(msg):
    print(msg)  # expect: GL010


def report_stderr(msg):
    sys.stderr.write(msg + "\n")  # expect: GL010


def report_suppressed(msg):
    print(msg)  # graftlint: disable=GL010


def report_good(msg):
    log_event(msg)  # clean: process-prefixed, mirrored to the span ring
