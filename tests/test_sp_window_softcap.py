"""Window/softcap composed with the sp axis (VERDICT r3 next #5).

The mistral family (sliding window) and gemma-2 style soft-capping must
sequence-parallelize: the ring turns banded with STATIC hop skipping
(out-of-band K/V chunks are never rotated or computed), ulysses gets both
for free (full local sequence per head group). Oracle: the dense einsum
with the same window/softcap.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mingpt_distributed_tpu.config import GPTConfig, MeshConfig
from mingpt_distributed_tpu.ops import attention as attn_ops
from mingpt_distributed_tpu.parallel import mesh as mesh_lib
from mingpt_distributed_tpu.parallel.ring_attention import ring_causal_attention
from mingpt_distributed_tpu.parallel.ulysses import ulysses_causal_attention


def sp_mesh(dp=1, sp=8, tp=1):
    return mesh_lib.make_mesh(
        MeshConfig(dp=dp, fsdp=1, tp=tp, sp=sp),
        devices=jax.devices()[: dp * tp * sp],
    )


def qkv(b=2, t=64, h=4, kv=None, hd=16, seed=0):
    kv = kv or h
    ks = jax.random.split(jax.random.key(seed), 3)
    return (
        jax.random.normal(ks[0], (b, t, h, hd)),
        jax.random.normal(ks[1], (b, t, kv, hd)),
        jax.random.normal(ks[2], (b, t, kv, hd)),
    )


# ---------------------------------------------------------------------------
# banded ring vs dense oracle
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("window,sp", [
    (1, 4),     # degenerate band: self-attention only
    (8, 4),     # band inside the own chunk (t_live = 1 boundary hop)
    (20, 4),    # band spans two past chunks
    (40, 4),    # band spans three
    (64, 4),    # window >= T: full causal through the banded path
    (11, 8),    # unaligned window, smallest chunks
    (16, 2),    # window == chunk
])
def test_banded_ring_matches_oracle(eight_devices, window, sp):
    mesh = sp_mesh(sp=sp)
    q, k, v = qkv(seed=window)
    want = attn_ops.causal_attention(q, k, v, window=window)
    got = jax.jit(lambda *a: ring_causal_attention(
        *a, mesh, window=window))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_banded_ring_with_softcap_matches_oracle(eight_devices):
    mesh = sp_mesh(sp=4)
    q, k, v = qkv(seed=23)
    want = attn_ops.causal_attention(q, k, v, window=20, logit_softcap=5.0)
    got = jax.jit(lambda *a: ring_causal_attention(
        *a, mesh, window=20, logit_softcap=5.0))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_softcap_ring_zigzag_matches_oracle(eight_devices):
    """softcap without a window routes through the zigzag ring."""
    mesh = sp_mesh(sp=4)
    q, k, v = qkv(seed=29)
    want = attn_ops.causal_attention(q, k, v, logit_softcap=4.0)
    got = jax.jit(lambda *a: ring_causal_attention(
        *a, mesh, logit_softcap=4.0))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_banded_ring_gradients_match_oracle(eight_devices):
    mesh = sp_mesh(dp=2, sp=4)
    q, k, v = qkv(seed=31)

    def loss(fn):
        return lambda q, k, v: jnp.sum(jnp.square(fn(q, k, v)))

    g_want = jax.grad(
        loss(lambda *a: attn_ops.causal_attention(
            *a, window=20, logit_softcap=5.0)), argnums=(0, 1, 2))(q, k, v)
    g_got = jax.jit(jax.grad(
        loss(lambda *a: ring_causal_attention(
            *a, mesh, window=20, logit_softcap=5.0)),
        argnums=(0, 1, 2)))(q, k, v)
    for want, got, name in zip(g_want, g_got, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4,
            err_msg=f"d{name}",
        )


def test_banded_ring_einsum_inner_fallback(eight_devices):
    """Non-tileable chunks (c=20) take the windowed einsum ring fold."""
    mesh = sp_mesh(dp=4, sp=2)
    q, k, v = qkv(b=4, t=40, h=2, seed=37)
    want = attn_ops.causal_attention(q, k, v, window=13, logit_softcap=3.0)
    got = jax.jit(lambda *a: ring_causal_attention(
        *a, mesh, window=13, logit_softcap=3.0))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_banded_ring_skips_out_of_band_hops(eight_devices, monkeypatch):
    """The work-accounting claim: with window W over chunks of c tokens,
    the ring executes ONLY 1 + min(n-1, (W+c-2)//c) kernel calls at trace
    time (python-unrolled hops) — chunks beyond the band are never
    rotated or attended. The contiguous/zigzag rings execute n-1 hops."""
    from mingpt_distributed_tpu.ops import flash_attention as fa

    sp, t = 8, 128  # c = 16 per device
    c = t // sp
    calls = []
    real = fa.flash_with_lse

    def counting(q, k, v, scale, block, causal=True, window=None,
                 softcap=None, q_offset=0):
        calls.append({"causal": causal, "window": window,
                      "q_offset": q_offset, "k_len": k.shape[1]})
        return real(q, k, v, scale, block, causal, window, softcap, q_offset)

    monkeypatch.setattr(fa, "flash_with_lse", counting)
    mesh = sp_mesh(sp=sp)

    # t_live = (W + c - 2) // c with c = 16: hop t is live iff its nearest
    # key, t*c - (c-1) tokens back, is within reach W-1 — so W=33 still
    # runs 2 hops (48-15 = 33 > 32) and W=34 is the 3-hop boundary
    for window, want_hops in [(8, 1), (20, 2), (33, 2), (34, 3)]:
        calls.clear()
        q, k, v = qkv(b=1, t=t, h=2, seed=window)
        got = jax.jit(lambda *a, w=window: ring_causal_attention(
            *a, mesh, window=w))(q, k, v)
        t_live = min(sp - 1, (window + c - 2) // c)
        assert t_live == want_hops, (window, t_live)
        assert len(calls) == 1 + t_live, (window, calls)
        # step 0 is the square banded-causal kernel on the own chunk
        assert calls[0] == {"causal": True, "window": window,
                            "q_offset": 0, "k_len": c}
        for hop, rec in enumerate(calls[1:], start=1):
            d = hop * c
            if d + c - 1 < window:  # fully in-band: unmasked kernel
                assert rec["causal"] is False and rec["q_offset"] == 0
            else:  # boundary: offset-banded kernel
                assert rec["causal"] is True and rec["q_offset"] == d
                assert rec["window"] == window
        # and it's still exact
        want = attn_ops.causal_attention(q, k, v, window=window)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# ulysses
# ---------------------------------------------------------------------------


def test_ulysses_window_softcap_matches_oracle(eight_devices):
    mesh = sp_mesh(sp=4)
    q, k, v = qkv(seed=41)
    want = attn_ops.causal_attention(q, k, v, window=20, logit_softcap=5.0)
    got = jax.jit(lambda *a: ulysses_causal_attention(
        *a, mesh, window=20, logit_softcap=5.0))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_window_gradients_match_oracle(eight_devices):
    mesh = sp_mesh(dp=2, sp=4)
    q, k, v = qkv(seed=43)

    def loss(fn):
        return lambda q, k, v: jnp.sum(jnp.square(fn(q, k, v)))

    g_want = jax.grad(
        loss(lambda *a: attn_ops.causal_attention(*a, window=24)),
        argnums=(0, 1, 2))(q, k, v)
    g_got = jax.jit(jax.grad(
        loss(lambda *a: ulysses_causal_attention(*a, mesh, window=24)),
        argnums=(0, 1, 2)))(q, k, v)
    for want, got, name in zip(g_want, g_got, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4,
            err_msg=f"d{name}",
        )


# ---------------------------------------------------------------------------
# model level: the mistral-shaped config sequence-parallelizes
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("attention", ["ring", "ulysses"])
def test_mistral_shaped_model_logits_match_dense(eight_devices, attention):
    """A mistral-tiny-shaped config (window + swiglu + rope + softcap) at
    sp=4 must produce the same logits as the dense einsum model — the
    model family that motivates sliding windows gets the sp axis."""
    from mingpt_distributed_tpu.models import gpt

    kw = dict(
        n_layer=2, n_head=4, n_embd=32, block_size=64, vocab_size=61,
        attention_window=24, attn_logit_softcap=8.0, swiglu=True, rope=True,
        rmsnorm=True, embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0,
        dtype="float32",  # isolate layout (sp) from bf16 reduction order
    )
    cfg_sp = GPTConfig.make(attention=attention, **kw)
    cfg_dense = GPTConfig.make(attention="einsum", **kw)
    params = gpt.init(jax.random.key(0), cfg_dense)
    idx = jax.random.randint(jax.random.key(1), (2, 64), 0, 61)

    want, _ = gpt.forward(params, idx, cfg_dense, deterministic=True)
    mesh = sp_mesh(sp=4)
    got, _ = jax.jit(lambda p, i: gpt.forward(
        p, i, cfg_sp, deterministic=True, mesh=mesh))(params, idx)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
