"""Mixture-of-experts (ops/moe.py) + expert parallelism over the ep axis.
The reference has a dense MLP only (SURVEY §2.2: EP/MoE absent,
model.py:179-184); these tests pin the routing math to the dense oracle
where they must coincide and check sharding/e2e training behaviour."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mingpt_distributed_tpu.config import ConfigError, GPTConfig, MeshConfig
from mingpt_distributed_tpu.models import generate as gen
from mingpt_distributed_tpu.models import gpt
from mingpt_distributed_tpu.ops import layers as L
from mingpt_distributed_tpu.ops import moe
from mingpt_distributed_tpu.parallel import mesh as mesh_lib


def test_single_expert_equals_dense_mlp():
    """E=1, k=1, ample capacity: routing is the identity, so the MoE layer
    must reproduce the dense GELU MLP with the same weights exactly."""
    d, f = 16, 32
    key = jax.random.key(0)
    x = jax.random.normal(key, (2, 8, d), jnp.float32)
    w1 = jax.random.normal(jax.random.key(1), (d, f)) * 0.2
    w2 = jax.random.normal(jax.random.key(2), (f, d)) * 0.2
    wr = jnp.zeros((d, 1))
    out, aux = moe.moe_mlp(
        x, wr, w1[None], w2[None], top_k=1, capacity_factor=2.0,
    )
    want = L.mlp_gelu(x, w1, None, w2, None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux), 1.0, rtol=1e-6)  # E * 1 * 1


def test_topk_routing_mixes_experts():
    d, f, e = 8, 16, 4
    x = jax.random.normal(jax.random.key(0), (1, 32, d), jnp.float32)
    wr = jax.random.normal(jax.random.key(1), (d, e))
    w1 = jax.random.normal(jax.random.key(2), (e, d, f)) * 0.2
    w2 = jax.random.normal(jax.random.key(3), (e, f, d)) * 0.2
    out1, _ = moe.moe_mlp(x, wr, w1, w2, top_k=1, capacity_factor=4.0)
    out2, _ = moe.moe_mlp(x, wr, w1, w2, top_k=2, capacity_factor=4.0)
    assert out1.shape == out2.shape == x.shape
    # k=2 folds in a second expert: outputs must differ from k=1
    assert float(jnp.abs(out1 - out2).max()) > 1e-6


def test_capacity_overflow_drops_not_crashes():
    d, f, e = 8, 16, 2
    x = jax.random.normal(jax.random.key(0), (1, 64, d), jnp.float32)
    # router heavily biased to expert 0 -> guaranteed overflow at tiny cap
    wr = jnp.zeros((d, e)).at[:, 0].set(5.0)
    w1 = jax.random.normal(jax.random.key(2), (e, d, f)) * 0.2
    w2 = jax.random.normal(jax.random.key(3), (e, f, d)) * 0.2
    out, aux = moe.moe_mlp(x, wr, w1, w2, top_k=1, capacity_factor=0.25)
    assert np.isfinite(np.asarray(out)).all() and np.isfinite(float(aux))
    # dropped tokens contribute zero (residual carries them in the block)
    norms = jnp.linalg.norm(out[0], axis=-1)
    assert float(jnp.min(norms)) == 0.0


def test_switch_k1_router_gets_task_gradient():
    """k=1 must scale expert output by the RAW router prob (Switch): with
    renormalised gates the weight is identically 1 and the router would get
    zero task-loss gradient — it could never learn to specialize."""
    d, f, e = 8, 16, 4
    x = jax.random.normal(jax.random.key(0), (1, 32, d), jnp.float32)
    params = {
        "wr": jax.random.normal(jax.random.key(1), (d, e)),
        "w1": jax.random.normal(jax.random.key(2), (e, d, f)) * 0.2,
        "w2": jax.random.normal(jax.random.key(3), (e, f, d)) * 0.2,
    }

    def task_loss(p):  # NO aux term — gradient must come from the task
        out, _ = moe.moe_mlp(x, p["wr"], p["w1"], p["w2"],
                             top_k=1, capacity_factor=2.0)
        return jnp.sum(out ** 2)

    g = jax.grad(task_loss)(params)
    assert float(jnp.abs(g["wr"]).max()) > 0


def test_grouped_dispatch_linear_memory():
    """Groups bound the one-hot dispatch to O(group * S), not O(S^2): the
    routed result must be identical whether S spans one group or many (with
    non-binding capacity)."""
    d, f, e = 8, 16, 2
    w1 = jax.random.normal(jax.random.key(2), (e, d, f)) * 0.2
    w2 = jax.random.normal(jax.random.key(3), (e, f, d)) * 0.2
    wr = jax.random.normal(jax.random.key(1), (d, e))
    x = jax.random.normal(jax.random.key(0), (2, moe.MAX_GROUP, d))
    out, _ = moe.moe_mlp(x, wr, w1, w2, top_k=1, capacity_factor=2.0)
    # same tokens as a single smaller batch (one group) must agree
    out_small, _ = moe.moe_mlp(x[:1], wr, w1, w2, top_k=1,
                               capacity_factor=2.0)
    np.testing.assert_allclose(np.asarray(out[:1]), np.asarray(out_small),
                               rtol=1e-5, atol=1e-5)


def test_gradients_flow_to_router_and_experts():
    d, f, e = 8, 16, 4
    x = jax.random.normal(jax.random.key(0), (1, 32, d), jnp.float32)
    params = {
        "wr": jax.random.normal(jax.random.key(1), (d, e)),
        "w1": jax.random.normal(jax.random.key(2), (e, d, f)) * 0.2,
        "w2": jax.random.normal(jax.random.key(3), (e, f, d)) * 0.2,
    }

    def loss(p):
        out, aux = moe.moe_mlp(x, p["wr"], p["w1"], p["w2"],
                               top_k=2, capacity_factor=2.0)
        return jnp.sum(out ** 2) + 0.01 * aux

    g = jax.grad(loss)(params)
    for name in ("wr", "w1", "w2"):
        assert float(jnp.abs(g[name]).max()) > 0, f"zero grad for {name}"


def test_moe_forward_and_loss():
    cfg = GPTConfig.make(
        n_layer=2, n_head=2, n_embd=32, vocab_size=64, block_size=16,
        embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0, dtype="float32",
        n_experts=4, moe_top_k=2,
    )
    params = gpt.init(jax.random.key(0), cfg)
    assert params["blocks"]["w_e1"].shape == (2, 4, 32, 128)
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, 64)
    logits, loss = gpt.forward(params, tokens, cfg, targets=tokens)
    assert logits.shape == (2, 16, 64) and np.isfinite(float(loss))
    # aux weight actually contributes: zero-weight config gives lower loss
    cfg0 = GPTConfig.make(
        n_layer=2, n_head=2, n_embd=32, vocab_size=64, block_size=16,
        embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0, dtype="float32",
        n_experts=4, moe_top_k=2, moe_aux_weight=0.0,
    )
    _, loss0 = gpt.forward(params, tokens, cfg0, targets=tokens)
    assert float(loss) > float(loss0)


def test_moe_generation_matches_dense_oracle():
    """The KV-cached decode path must route identically to gpt.forward.

    Capacity must not bind (factor=E makes cap >= tokens): capacity-dropped
    tokens depend on how many tokens are evaluated together, so incremental
    decode only matches a full re-forward when nothing is dropped."""
    from tests.test_generate import dense_greedy

    cfg = GPTConfig.make(
        n_layer=2, n_head=2, n_embd=32, vocab_size=50, block_size=32,
        embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0, dtype="float32",
        n_experts=2, moe_top_k=1, moe_capacity_factor=2.0,
    )
    params = gpt.init(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(1), (2, 5), 0, 50)
    want = dense_greedy(params, cfg, prompt, 8)
    got = gen.generate(params, cfg, prompt, 8)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


def test_moe_sharded_matches_unsharded(eight_devices):
    """ep=4 sharding is layout, not semantics: logits must match the
    single-device forward bit-closely (GSPMD inserts the all-to-alls)."""
    cfg = GPTConfig.make(
        n_layer=2, n_head=2, n_embd=32, vocab_size=64, block_size=16,
        embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0, dtype="float32",
        n_experts=4, moe_top_k=2,
    )
    params = gpt.init(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (8, 16), 0, 64)
    want, want_loss = gpt.forward(params, tokens, cfg, targets=tokens)

    mesh = mesh_lib.make_mesh(
        MeshConfig(dp=2, fsdp=1, ep=4, tp=1, sp=1), devices=eight_devices
    )
    shardings = mesh_lib.param_shardings(
        mesh, jax.eval_shape(lambda: params)
    )
    sharded = jax.device_put(params, shardings)
    got, got_loss = jax.jit(
        lambda p, t: gpt.forward(p, t, cfg, targets=t)
    )(sharded, jax.device_put(tokens, mesh_lib.batch_sharding(mesh)))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(float(got_loss), float(want_loss), rtol=1e-5)


def test_moe_trainer_learns(tmp_path, eight_devices):
    """End-to-end: an MoE model trains under the jitted sharded train step
    and the loss goes down; expert params land sharded over ep."""
    from tests.test_trainer import CORPUS

    from mingpt_distributed_tpu.config import (
        DataConfig, OptimizerConfig, TrainerConfig,
    )
    from mingpt_distributed_tpu.data.char_dataset import CharDataset
    from mingpt_distributed_tpu.training.trainer import GPTTrainer

    ds = CharDataset(
        DataConfig(path="<inline>", block_size=16, train_split=0.9),
        text=CORPUS,
    )
    train, test = ds.split()
    gcfg = GPTConfig.make(
        n_layer=2, n_head=2, n_embd=32, vocab_size=ds.vocab_size,
        block_size=16, embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0,
        dtype="float32", n_experts=4, moe_top_k=2,
    )
    tcfg = TrainerConfig.make(
        max_epochs=1, batch_size=16, grad_norm_clip=1.0, save_every=100,
        log_every=1000, seed=7, max_steps=8,
        snapshot_path=str(tmp_path / "moe2.msgpack"),
    )
    mesh = mesh_lib.make_mesh(
        MeshConfig(dp=2, fsdp=1, ep=2, tp=1, sp=1), devices=eight_devices[:4]
    )
    tr = GPTTrainer(tcfg, gcfg, OptimizerConfig(learning_rate=1e-2),
                    train, test, mesh=mesh)
    w_e1 = tr.state["params"]["blocks"]["w_e1"]  # (L, E, D, F)
    assert w_e1.addressable_shards[0].data.shape[1] == w_e1.shape[1] // 2
    first, last = None, None
    for xy in tr.train_iter.epoch_batches():
        tr.state, m = tr._train_step(tr.state, tr._put_batch(xy), tr.base_rng)
        loss = float(jax.device_get(m["loss"]))
        first = first if first is not None else loss
        last = loss
        if tr.train_iter.state.step_in_epoch >= 8:
            break
    assert last < first  # it learns


def test_moe_config_validation():
    with pytest.raises(ConfigError, match="moe_top_k"):
        GPTConfig.make(n_layer=2, n_head=2, n_embd=32, n_experts=2,
                       moe_top_k=3)


def test_swiglu_single_expert_equals_dense_swiglu():
    """Mixtral-style SwiGLU experts: E=1 must reduce to the dense SwiGLU MLP
    with the same weights."""
    d, f = 16, 32
    x = jax.random.normal(jax.random.key(0), (2, 8, d), jnp.float32)
    wg = jax.random.normal(jax.random.key(1), (d, f)) * 0.2
    wu = jax.random.normal(jax.random.key(2), (d, f)) * 0.2
    wd = jax.random.normal(jax.random.key(3), (f, d)) * 0.2
    out, _ = moe.moe_mlp(
        x, jnp.zeros((d, 1)), wu[None], wd[None], top_k=1,
        capacity_factor=2.0, w_gate=wg[None],
    )
    want = L.mlp_swiglu(x, wg, wu, wd)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_mixtral_style_model_trains_and_generates():
    """llama toggles + MoE together (the Mixtral family): forward, loss,
    grads, and KV-cached generation parity."""
    from tests.test_generate import dense_greedy

    cfg = GPTConfig.make(
        n_layer=2, n_head=2, n_embd=32, vocab_size=50, block_size=32,
        embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0, dtype="float32",
        rope=True, swiglu=True, rmsnorm=True, n_kv_head=1, tie_weights=True,
        n_experts=2, moe_top_k=2, moe_capacity_factor=2.0,
    )
    params = gpt.init(jax.random.key(0), cfg)
    assert params["blocks"]["w_eg"].shape == params["blocks"]["w_e1"].shape
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, 50)
    _, loss = gpt.forward(params, tokens, cfg, targets=tokens)
    assert np.isfinite(float(loss))
    g = jax.grad(
        lambda p: gpt.forward(p, tokens, cfg, targets=tokens)[1]
    )(params)
    assert float(jnp.abs(g["blocks"]["w_eg"]).max()) > 0
    prompt = jax.random.randint(jax.random.key(2), (1, 4), 0, 50)
    want = dense_greedy(params, cfg, prompt, 6)
    got = gen.generate(params, cfg, prompt, 6)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


def test_mixtral_preset_forward():
    """The mixtral presets resolve and run: SwiGLU experts + top-2 routing +
    rope/rmsnorm/GQA composed via one model_type string."""
    import dataclasses

    from mingpt_distributed_tpu.config import GPTConfig

    cfg = GPTConfig.make(model_type="mixtral-tiny", block_size=16,
                         vocab_size=64, dtype="float32",
                         embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0)
    assert cfg.n_experts == 4 and cfg.moe_top_k == 2 and cfg.swiglu
    params = gpt.init(jax.random.key(0), cfg)
    toks = jax.random.randint(jax.random.key(1), (2, 16), 0, 64)
    logits, loss = gpt.forward(params, toks, cfg, targets=toks)
    assert logits.shape == (2, 16, 64)
    assert np.isfinite(float(loss))
