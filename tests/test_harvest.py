"""tools/harvest.py contract: serial stages, artifact index, resume.

Mirrors tests/test_bench.py's approach — fake stages (tiny python -c
scripts) stand in for the chip-touching commands, so the probe -> run ->
index -> resume machinery is CI-tested on CPU without hardware.
"""

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_harvest(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "harvest", os.path.join(REPO, "tools", "harvest.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # redirect the index into the sandbox so tests never touch the repo's
    monkeypatch.setattr(mod, "INDEX", str(tmp_path / "HARVEST.json"))
    return mod


def _ok_stage(name, tmp_path, marker=None):
    art = str(tmp_path / f"{name}.out")
    marker = marker or str(tmp_path / f"{name}.ran")
    return {
        "name": name,
        # the marker file counts executions so resume behavior is provable
        "argv": [sys.executable, "-c",
                 f"open({marker!r}, 'a').write('x')"],
        "artifact": art,
        "_marker": marker,
    }


def _runs(stage):
    try:
        with open(stage["_marker"]) as f:
            return len(f.read())
    except OSError:
        return 0


def test_harvest_runs_all_stages_and_writes_index(tmp_path, monkeypatch):
    h = _load_harvest(tmp_path, monkeypatch)
    stages = [_ok_stage("a", tmp_path), _ok_stage("b", tmp_path)]
    ok = h.harvest(stages, cooldown_s=0,
                   probe={"platform": "tpu", "kind": "fake"})
    assert ok
    index = json.loads((tmp_path / "HARVEST.json").read_text())
    assert index["complete"] is True
    assert index["backend"]["kind"] == "fake"
    assert index["stages"]["a"]["status"] == "ok"
    assert index["stages"]["b"]["status"] == "ok"
    assert _runs(stages[0]) == 1 and _runs(stages[1]) == 1


def test_harvest_resumes_skipping_completed_stages(tmp_path, monkeypatch):
    h = _load_harvest(tmp_path, monkeypatch)
    good = _ok_stage("good", tmp_path)
    bad = {
        "name": "bad",
        "argv": [sys.executable, "-c", "import sys; sys.exit(1)"],
        "artifact": str(tmp_path / "bad.out"),
    }
    ok = h.harvest([good, bad], cooldown_s=0)
    assert not ok
    index = json.loads((tmp_path / "HARVEST.json").read_text())
    assert index["complete"] is False
    assert index["stages"]["bad"]["status"] == "failed"

    # second contact window: the completed stage must NOT re-run (single
    # chip time is precious), the failed one must retry
    fixed = dict(bad, argv=_ok_stage("bad2", tmp_path)["argv"],
                 _marker=str(tmp_path / "bad2.ran"))
    ok = h.harvest([good, fixed], cooldown_s=0)
    assert ok
    assert _runs(good) == 1, "completed stage re-ran on resume"
    index = json.loads((tmp_path / "HARVEST.json").read_text())
    assert index["complete"] is True


def test_harvest_stage_timeout_is_bounded(tmp_path, monkeypatch):
    h = _load_harvest(tmp_path, monkeypatch)
    hang = {
        "name": "hang",
        "argv": [sys.executable, "-c", "import time; time.sleep(60)"],
        "artifact": str(tmp_path / "hang.out"),
    }
    ok = h.harvest([hang], cooldown_s=0, stage_timeout_s=1.0)
    assert not ok
    index = json.loads((tmp_path / "HARVEST.json").read_text())
    assert index["stages"]["hang"]["status"] == "timeout"


def test_bench_stage_parses_json_and_fails_on_error_record(
        tmp_path, monkeypatch):
    h = _load_harvest(tmp_path, monkeypatch)
    art = tmp_path / "bench.json"
    # a bench error record (value null) must count as a FAILED stage so a
    # later window retries the measurement, not a success with no number
    err_stage = {
        "name": "bench",
        "argv": [sys.executable, "-c",
                 "print('noise'); "
                 "print('{\"metric\": \"m\", \"value\": null, "
                 "\"error\": \"tunnel down\"}')"],
        "artifact": str(art),
        "capture_json": True,
    }
    assert not h.harvest([err_stage], cooldown_s=0)
    assert json.loads(art.read_text())["error"] == "tunnel down"

    good_stage = dict(err_stage, argv=[
        sys.executable, "-c",
        "print('{\"metric\": \"m\", \"value\": 0.5}')"])
    assert h.harvest([good_stage], cooldown_s=0)
    assert json.loads(art.read_text())["value"] == 0.5


def test_optional_stage_with_missing_binary_is_skipped(tmp_path, monkeypatch):
    h = _load_harvest(tmp_path, monkeypatch)
    stage = {
        "name": "native",
        "argv": [str(tmp_path / "not_built"), "arg"],
        "artifact": str(tmp_path / "native.out"),
        "optional": True,
    }
    ok = h.harvest([stage], cooldown_s=0)
    assert ok, "missing optional binary must not fail the harvest"
    index = json.loads((tmp_path / "HARVEST.json").read_text())
    assert index["stages"]["native"]["status"] == "skipped"


def test_index_survives_torn_write(tmp_path, monkeypatch):
    h = _load_harvest(tmp_path, monkeypatch)
    (tmp_path / "HARVEST.json").write_text("{torn")
    assert h.load_index() == {"stages": {}}


def test_default_stage_table_shape():
    """The real stage table must reference existing scripts and keep the
    serialized order preflight -> bench -> profile -> pjrt_smoke."""
    spec = importlib.util.spec_from_file_location(
        "harvest", os.path.join(REPO, "tools", "harvest.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    stages = mod.default_stages()
    names = [s["name"] for s in stages]
    assert names == ["chip_preflight", "bench", "bench_profile",
                     "pjrt_smoke", "exp_btd_fused_ab", "exp_decode"]
    for s in stages:
        # every non-optional stage's entry script must exist in-tree
        if not s.get("optional"):
            base = os.path.basename(s["argv"][0])
            path = s["argv"][1] if base.startswith("python") else s["argv"][0]
            assert os.path.exists(path), path
