"""Packaging (reference parity: /root/reference/setup.py:1-11).

The reference packages `mingpt` 0.0.1 requiring torch+hydra-core; here the
package is the TPU-native framework and the deps are the JAX stack (all baked
into the target image — keep install_requires minimal and pin-free).
"""

from setuptools import find_packages, setup

setup(
    name="mingpt-distributed-tpu",
    version="0.1.0",
    description=(
        "A TPU-native (JAX/XLA/Pallas/pjit) re-implementation of GPT trained "
        "on multiple hosts — capabilities of minGPT-distributed, rebuilt "
        "TPU-first"
    ),
    packages=find_packages(include=["mingpt_distributed_tpu*"]),
    python_requires=">=3.10",
    install_requires=[
        "jax",
        "optax",
        "pyyaml",
        "numpy",
        "fsspec",
    ],
    extras_require={
        "s3": ["boto3", "s3fs"],
        "gcs": ["gcsfs"],
        "test": ["pytest"],
    },
)
