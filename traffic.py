#!/usr/bin/env python
"""Traffic lab CLI: open-loop load sweeps over the serving fleet.

Drives mingpt_distributed_tpu/trafficlab end to end: a seeded arrival
process (Poisson / bursty / ramp) is offered at each rung of a load
ladder, every admission policy (fifo / edf / fair) replays the
IDENTICAL arrival trace per rung against a fresh fleet on VirtualClock,
each (rung, policy) cell is graded by the telemetry SLO engine, and the
result is a versioned ``mingpt-traffic/1`` JSON report with the knee
rung (first rung where the named objective fails). Zero wall-clock
reads: a multi-rung sweep finishes in seconds of real time regardless
of the virtual load, and the same seed reproduces the report
byte-for-byte.

Modes:

  sweep (default)     restore the training snapshot (as serve.py does)
                      and sweep it:
                        python traffic.py --arrival poisson:rate=60 \
                            --ladder 1,2,4 --policies fifo,edf --out r.json
                      (--random-init skips the checkpoint: random weights,
                      config dims — latency shape only, no real text)
  self-test           random-init tiny model, 2-rung FIFO-vs-EDF sweep on
                      a deadline-mixed workload; asserts the report
                      strict-parses, the knee is located (objective passes
                      at rung 0, fails at rung 1), EDF >= FIFO on
                      deadline-hit-rate at the overload rung, and a second
                      run is byte-identical — the CI gate
                      (run_tests.sh --selftest-traffic):
                        python traffic.py --selftest-traffic

Knobs: --arrival SPEC (poisson:rate=R | bursty:rate_on=..:rate_off=..:
period=..:duty=.. | ramp:rate0=..:rate1=..:duration=..), --ladder
"f1,f2,..." (load multipliers, strictly increasing), --policies
"fifo,edf,fair", --requests N per rung, --seed, --replicas/--slots
(fleet geometry), --slo SPEC (telemetry/slo.py grammar),
--knee-objective NAME (default: first objective), --chaos-spec SPEC
(ServingFaultInjector grammar — the same sweep graded under crashes),
--controllers "static,auto:..." (SLO-autoscaler axis: every policy
runs once per controller on the identical trace), --shed-watermark D,
--prefix-cache-mb M, --out PATH (report JSON).
"""

from __future__ import annotations

import argparse
import dataclasses
import sys


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", default="gpt2_config.yaml")
    p.add_argument("--arrival", default="poisson:rate=60.0",
                   help="base arrival spec (see module docstring); the "
                        "ladder multiplies its rates")
    p.add_argument("--ladder", default="1,2,4",
                   help="comma-separated load factors, strictly increasing")
    p.add_argument("--policies", default="fifo,edf",
                   help="admission policies to compare on the identical "
                        "trace (fifo | edf | fair)")
    p.add_argument("--requests", type=int, default=64,
                   help="arrivals per rung")
    p.add_argument("--seed", type=int, default=0,
                   help="replay seed: (seed, specs) fully determine the "
                        "report bytes")
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--slots", type=int, default=4,
                   help="KV slots per replica")
    p.add_argument("--tick-s", type=float, default=0.001,
                   help="virtual seconds per fleet scheduling round")
    p.add_argument("--slo", default="default",
                   help="SLO spec to grade each cell with "
                        "(telemetry/slo.py grammar; 'default' = stock "
                        "objectives)")
    p.add_argument("--knee-objective", default=None,
                   help="objective name the knee is located on (default: "
                        "first objective in --slo)")
    p.add_argument("--chaos-spec", default=None,
                   help="ServingFaultInjector spec: grade the same sweep "
                        "under injected faults")
    p.add_argument("--hosts", type=int, default=1,
                   help="> 1 runs every cell on the loopback cross-host "
                        "mesh (--replicas becomes per-host)")
    p.add_argument("--net-chaos-spec", default=None,
                   help="NetworkFaultInjector spec (partition / "
                        "drop_frame / slow_link / host_kill) over the "
                        "host mesh; needs --hosts >= 2")
    p.add_argument("--controllers", default="static",
                   help="comma-separated controller axis: each entry is "
                        "'static' or an 'auto[:k=v...]' SLO-autoscaler "
                        "spec; every policy runs once per controller on "
                        "the identical trace (autoscaled cells are "
                        "labelled policy+auto)")
    p.add_argument("--shed-watermark", type=int, default=None,
                   help="fleet-wide queue depth that sheds new arrivals")
    p.add_argument("--prefix-cache-mb", type=float, default=0.0,
                   help="per-replica shared-prefix KV budget (MiB); >0 "
                        "lets shared-prefix tenants hit the store")
    p.add_argument("--out", default=None,
                   help="write the mingpt-traffic/1 report JSON here")
    p.add_argument("--random-init", action="store_true",
                   help="skip checkpoint restore: random weights at the "
                        "config's dims (scheduling/latency study only)")
    p.add_argument("--selftest-traffic", action="store_true",
                   help="tiny random-init model, canned 2-rung FIFO/EDF "
                        "sweep; asserts knee + policy separation + "
                        "byte-identical replay, then exits")
    p.add_argument("--selftest-controller", action="store_true",
                   help="tiny random-init model, one down-ramp rung, "
                        "static vs SLO-autoscaled cells on the identical "
                        "trace; asserts the controller scales up AND back "
                        "down, beats static on deadline hit-rate and "
                        "cost, and replays byte-identically (report and "
                        "mingpt-control/1 log), then exits")
    p.add_argument("overrides", nargs="*")
    return p


def _parse_ladder(text: str):
    try:
        ladder = tuple(float(f) for f in text.split(",") if f.strip())
    except ValueError:
        raise SystemExit(f"--ladder must be comma-separated floats, "
                         f"got {text!r}")
    if not ladder:
        raise SystemExit("--ladder is empty")
    return ladder


def _sweep_spec(args):
    from mingpt_distributed_tpu.trafficlab import SweepSpec

    spec = SweepSpec(
        arrival=args.arrival,
        ladder=_parse_ladder(args.ladder),
        policies=tuple(p.strip() for p in args.policies.split(",")
                       if p.strip()),
        n_requests=args.requests,
        seed=args.seed,
        n_replicas=args.replicas,
        n_slots=args.slots,
        tick_s=args.tick_s,
        slo=args.slo,
        knee_objective=args.knee_objective,
        chaos_spec=args.chaos_spec,
        n_hosts=args.hosts,
        net_chaos_spec=args.net_chaos_spec,
        controllers=tuple(c.strip() for c in args.controllers.split(",")
                          if c.strip()),
        shed_watermark=args.shed_watermark,
        prefix_cache_mb=args.prefix_cache_mb,
    )
    try:
        spec.validate()
    except ValueError as e:
        raise SystemExit(f"bad sweep parameters: {e}")
    return spec


def _tiny_model():
    """The repo-standard tiny random-init model (serve.py --selftest
    geometry): CPU-fast, real compiled prefill/decode."""
    import jax

    from mingpt_distributed_tpu.config import GPTConfig
    from mingpt_distributed_tpu.models import gpt

    cfg = GPTConfig.make(
        n_layer=2, n_head=2, n_embd=32, vocab_size=96, block_size=48,
        embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0, dtype="float32",
    )
    return cfg, gpt.init(jax.random.key(0), cfg)


def selftest_mix():
    """The tuned selftest workload: a deadline-tight chat tenant that
    EDF saves under overload, a deadline-free batch tenant whose long
    decodes clog FIFO queues, and a shared-prefix tenant for the
    PrefixKVStore. Geometry chosen so the overload rung's queue waits
    overrun the chat deadline under FIFO but not under EDF."""
    from mingpt_distributed_tpu.trafficlab import TenantSpec, WorkloadMix

    return WorkloadMix(vocab_size=96, tenants=(
        TenantSpec(name="chat", family="chat", weight=3.0,
                   prompt_len=(3, 8), max_new=(2, 4), deadline_s=0.035),
        TenantSpec(name="batch", family="completion", weight=3.0,
                   prompt_len=(4, 10), max_new=(10, 16)),
        TenantSpec(name="assist", family="prefix", weight=2.0,
                   prompt_len=(8, 14), max_new=(2, 6), deadline_s=0.08,
                   prefix_pool=2, prefix_len=6),
    ))


def selftest_sweep_spec(ladder=(1.0, 24.0)):
    """Canned selftest sweep: rung 0 well under the 1x2-slot fleet's
    capacity, the last rung strongly over it (tuned empirically: at 24x
    the p95 queue wait is ~3x the knee threshold)."""
    from mingpt_distributed_tpu.trafficlab import SweepSpec

    return SweepSpec(
        arrival="poisson:rate=40.0",
        ladder=ladder,
        policies=("fifo", "edf"),
        n_requests=40,
        seed=0,
        n_replicas=1,
        n_slots=2,
        slo="ttft_p95<=0.025,shed_rate<=0.5",
        prefix_cache_mb=0.5,
    )


def selftest_traffic(args) -> int:
    """The CI gate (run_tests.sh --selftest-traffic). Asserts, on the
    canned geometry: strict report validation after a JSON round-trip,
    knee located with the pass->fail shape, EDF >= FIFO on
    deadline-hit-rate at the overload rung (same trace — the report's
    trace_sha256 proves it), and byte-identical replay."""
    import json

    from mingpt_distributed_tpu.trafficlab import (
        render_traffic_report,
        run_sweep,
        validate_traffic_report,
    )
    from mingpt_distributed_tpu.trafficlab.report import dump_report

    cfg, params = _tiny_model()
    spec = selftest_sweep_spec()
    mix = selftest_mix()
    report = run_sweep(params, cfg, spec, mix=mix)
    print(render_traffic_report(report))

    rc = 0

    def check(ok: bool, what: str) -> None:
        nonlocal rc
        print(f"selftest-traffic {'OK' if ok else 'FAIL'}: {what}")
        if not ok:
            rc = 1

    # strict validation must survive a serialize/parse round-trip (the
    # report a consumer reads, not the in-memory dict)
    parsed = json.loads(dump_report(report))
    problems = validate_traffic_report(parsed, strict=False)
    check(not problems, f"report strict-parses (problems={problems})")

    knee = parsed.get("knee")
    check(knee is not None and knee["valid"],
          f"knee located with pass->fail shape (knee={knee})")

    last = parsed["rungs"][-1]
    fifo_cell = last["policies"]["fifo"]
    edf_cell = last["policies"]["edf"]
    fifo_hit = fifo_cell["deadline_hit_rate"]
    edf_hit = edf_cell["deadline_hit_rate"]
    check(fifo_hit is not None and edf_hit is not None
          and edf_hit >= fifo_hit,
          f"EDF >= FIFO on deadline-hit-rate at overload rung "
          f"(edf={edf_hit} fifo={fifo_hit})")
    check(edf_hit is not None and fifo_hit is not None
          and edf_hit > fifo_hit,
          "separation is strict on the canned geometry")

    report2 = run_sweep(params, cfg, spec, mix=mix)
    check(dump_report(report) == dump_report(report2),
          "same-seed rerun is byte-identical")

    print("selftest-traffic " + ("PASSED" if rc == 0 else "FAILED"))
    return rc


AUTO_SPEC = ("auto:metric=queue_depth:target=2.0:comfort=0.5"
             ":interval_s=0.002:cooldown_s=0.02:up_after=2:down_after=5"
             ":min_replicas=1:max_replicas=3")


def selftest_controller_spec():
    """Canned controller geometry: a DOWN-ramp so one cell exercises
    both directions — the early burst (~300/s against a 1x2-slot
    fleet) forces scale-ups, the sparse tail (~6/s) leaves the extra
    replicas comfortable long enough to drain back down."""
    from mingpt_distributed_tpu.trafficlab import SweepSpec

    return SweepSpec(
        arrival="ramp:rate0=1400.0:rate1=4.0:duration=0.04",
        ladder=(1.0,),
        policies=("fifo",),
        controllers=("static", AUTO_SPEC),
        n_requests=36,
        seed=0,
        n_replicas=1,
        n_slots=2,
        slo="ttft_p95<=0.025,shed_rate<=0.5",
        prefix_cache_mb=0.5,
    )


def selftest_controller(args) -> int:
    """The CI gate (run_tests.sh --selftest-controller). Static and
    autoscaled cells replay the IDENTICAL down-ramp trace; asserts the
    controller logs >= 1 replica scale-up and >= 1 scale-down, beats
    the static fleet on deadline hit-rate AND cost-model cost at the
    overload rung, the report strict-parses, every control-log line is
    a valid mingpt-control/1 row, and a rerun reproduces both the
    report and the control log byte-for-byte."""
    import json

    from mingpt_distributed_tpu.control.controller import CONTROL_SCHEMA
    from mingpt_distributed_tpu.trafficlab import (
        render_traffic_report,
        run_sweep,
        validate_traffic_report,
    )
    from mingpt_distributed_tpu.trafficlab.report import dump_report

    cfg, params = _tiny_model()
    spec = selftest_controller_spec()
    mix = selftest_mix()

    def run_once():
        logs = {}
        report = run_sweep(
            params, cfg, spec, mix=mix,
            control_log_sink=lambda r, lb, text: logs.__setitem__(
                (r, lb), text))
        return report, logs

    report, logs = run_once()
    print(render_traffic_report(report))

    rc = 0

    def check(ok: bool, what: str) -> None:
        nonlocal rc
        print(f"selftest-controller {'OK' if ok else 'FAIL'}: {what}")
        if not ok:
            rc = 1

    parsed = json.loads(dump_report(report))
    problems = validate_traffic_report(parsed, strict=False)
    check(not problems, f"report strict-parses (problems={problems})")
    check(parsed["policies"] == ["fifo", "fifo+auto"],
          f"cell labels carry the controller axis ({parsed['policies']})")

    rung = parsed["rungs"][0]
    static_cell = rung["policies"]["fifo"]
    auto_cell = rung["policies"]["fifo+auto"]
    control = auto_cell.get("control") or {}
    rep_actions = (control.get("actions") or {}).get("replicas", {})
    check(rep_actions.get("up", 0) >= 1,
          f"controller scaled up (replica actions={rep_actions})")
    check(rep_actions.get("down", 0) >= 1,
          f"controller scaled back down (replica actions={rep_actions})")

    s_hit, a_hit = (static_cell["deadline_hit_rate"],
                    auto_cell["deadline_hit_rate"])
    check(s_hit is not None and a_hit is not None and a_hit > s_hit,
          f"autoscaled beats static on deadline hit-rate "
          f"(auto={a_hit} static={s_hit})")
    s_cost, a_cost = static_cell["cost"]["cost"], auto_cell["cost"]["cost"]
    check(a_cost < s_cost,
          f"autoscaled cell is cheaper under the cost model "
          f"(auto={a_cost:.6g} static={s_cost:.6g})")

    log_text = logs.get((0, "fifo+auto"), "")
    rows = [json.loads(line) for line in log_text.splitlines()]
    check(bool(rows) and all(r.get("schema") == CONTROL_SCHEMA
                             for r in rows),
          f"control log is valid {CONTROL_SCHEMA} JSONL ({len(rows)} rows)")
    check(control.get("ticks") == len(rows),
          f"cell ticks match log rows ({control.get('ticks')} vs "
          f"{len(rows)})")

    report2, logs2 = run_once()
    check(dump_report(report) == dump_report(report2),
          "same-seed rerun report is byte-identical")
    check(logs == logs2, "same-seed rerun control log is byte-identical")

    print("selftest-controller " + ("PASSED" if rc == 0 else "FAILED"))
    return rc


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    if args.selftest_traffic:
        return selftest_traffic(args)
    if args.selftest_controller:
        return selftest_controller(args)

    from mingpt_distributed_tpu.config import load_config
    from mingpt_distributed_tpu.trafficlab import (
        render_traffic_report,
        run_sweep,
    )
    from mingpt_distributed_tpu.trafficlab.report import dump_report

    spec = _sweep_spec(args)
    cfg = load_config(args.config, args.overrides)
    gpt_cfg = dataclasses.replace(
        cfg.gpt_config,
        embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0,
    ).resolved()
    if args.random_init:
        import jax

        from mingpt_distributed_tpu.models import gpt

        params = gpt.init(jax.random.key(0), gpt_cfg)
        print(f"random-init model at {gpt_cfg.n_layer}L/"
              f"{gpt_cfg.n_embd}d (no checkpoint)", file=sys.stderr)
    else:
        import jax

        from mingpt_distributed_tpu.data.token_dataset import make_dataset
        from mingpt_distributed_tpu.training import checkpoint as ckpt_lib

        dataset = make_dataset(cfg.data_config)
        gpt_cfg = dataclasses.replace(
            gpt_cfg, vocab_size=dataset.vocab_size,
            block_size=dataset.block_size)
        path = (cfg.trainer_config.snapshot_path
                or ckpt_lib.DEFAULT_SNAPSHOT_PATH)
        snap = ckpt_lib.restore_inference_params(path, gpt_cfg)
        if snap is None:
            print(f"no snapshot at {path}; train first or pass "
                  f"--random-init", file=sys.stderr)
            return 1
        params = jax.device_put(snap.params)
        print(f"loaded snapshot step {snap.step} from {path}",
              file=sys.stderr)

    report = run_sweep(params, gpt_cfg, spec)
    print(render_traffic_report(report))
    if args.out is not None:
        with open(args.out, "w") as f:
            f.write(dump_report(report))
        print(f"mingpt-traffic/1 report written to {args.out}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
