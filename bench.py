#!/usr/bin/env python
"""Benchmark: GPT-2 124M training-step throughput + MFU on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

The reference publishes no numbers (SURVEY §6; BASELINE.json "published": {});
the driver-set north star is >=80% MFU on GPT-2 124M at seq 1024, so
``vs_baseline`` reports measured-MFU / 0.80.

The measured program is the full jitted training step (forward + backward +
AdamW update, donated state) — the same compiled unit the trainer runs, not a
matmul microbench.  Both attention paths are measured (flash Pallas kernel and
the einsum oracle); the headline number is the faster one and both appear in
the record.

Failure containment (VERDICT.md round 1, Missing #1 / Weak #2): the backend is
probed in a time-bounded subprocess before anything imports jax in-process, and
the measurement itself runs in a bounded subprocess — so an unreachable TPU
tunnel produces a JSON record with an "error" field in bounded time instead of
a hang or a raw traceback.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

METRIC = "mfu_gpt2_124m_seq1024"


def _env_num(name: str, default, cast):
    """Env override that can never break the one-JSON-line contract: a
    malformed value falls back to the default instead of raising."""
    try:
        val = cast(os.environ[name])
    except (KeyError, ValueError):
        return default
    return val if val >= 0 else default


PROBE_TIMEOUT_S = _env_num("BENCH_PROBE_TIMEOUT_S", 240, int)


# VERDICT r2: a single 240 s probe converted a flaky-but-recoverable tunnel
# into a null round artifact.  Retry with backoff, bounded at ~28 min worst
# case (6 x 240 s timeouts + 5 x 45 s backoffs).
PROBE_ATTEMPTS = _env_num("BENCH_PROBE_ATTEMPTS", 6, int)
PROBE_BACKOFF_S = _env_num("BENCH_PROBE_BACKOFF_S", 45.0, float)
BENCH_TIMEOUT_S = 2400

# Error signatures worth retrying: tunnel/backend reachability flaps. A
# permanent failure (ImportError, bad venv) answers in ~1 s and must fail
# fast rather than burn the full retry budget on an unwinnable probe.
# Signatures are SPECIFIC (grpc status names, errno phrases) rather than
# bare substrings like "connection" — an ImportError whose message merely
# mentions a module named connection must not burn ~28 min of retries.
_TRANSIENT_MARKERS = (
    "timed out", "unavailable", "deadline_exceeded", "deadline exceeded",
    "connection refused", "connection reset", "failed to connect",
    "unreachable", "socket closed", "no json",
)

# Exception types the probe subprocess can classify itself: these answer
# instantly and no retry can fix them.
_PERMANENT_ETYPES = (
    "ImportError", "ModuleNotFoundError", "SyntaxError", "AttributeError",
    "NameError",
)


def _is_transient(msg: str, etype: str | None = None) -> bool:
    """Structured etype (from the probe subprocess) beats substring
    matching; the markers are the fallback for crashes that die before
    printing JSON."""
    if etype in _PERMANENT_ETYPES:
        return False
    low = msg.lower()
    return any(m in low for m in _TRANSIENT_MARKERS)


def _error_record(msg: str) -> dict:
    return {
        "metric": METRIC,
        "value": None,
        "unit": "fraction",
        "vs_baseline": None,
        "error": msg,
    }


def _probe_backend() -> dict:
    """Check jax.devices() answers within a bound; never imports jax here.

    The subprocess catches its own exception and reports the TYPE, so the
    parent classifies transient-vs-permanent structurally instead of by
    substring-matching a traceback (ADVICE r3: 'connect' in a module path
    must not look like a tunnel flap)."""
    code = (
        "import json, sys\n"
        "try:\n"
        "    import jax\n"
        "    d = jax.devices()[0]\n"
        "    print(json.dumps({'platform': d.platform,"
        " 'kind': d.device_kind, 'n': jax.device_count()}))\n"
        "except Exception as e:\n"
        "    print(json.dumps({'error': str(e)[:400] or type(e).__name__,"
        " 'etype': type(e).__name__}))\n"
        "    sys.exit(0)\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=PROBE_TIMEOUT_S,
        )
    except subprocess.TimeoutExpired:
        return {"error": f"backend probe timed out after {PROBE_TIMEOUT_S}s"}
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-3:]
        return {"error": "backend probe failed: " + " | ".join(tail)}
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": "backend probe produced no JSON"}


def _cpu_fallback_record(probe_error: str) -> dict | None:
    """Smaller-geometry CPU measurement for when the accelerator probe is
    dead (the mfu trajectory was null for five straight rounds because a
    240 s probe timeout produced an error record and nothing else). Runs
    the same inner sweep on the CPU backend with a small model/short
    sequence so the metric records a *real, clearly-labelled* number —
    MFU against a measured CPU matmul peak — instead of null. Returns the
    parsed record (tagged backend=cpu_fallback) or None if even the CPU
    run failed."""
    env = dict(
        os.environ,
        # force the hermetic CPU backend the test wrapper uses: the
        # ambient TPU-plugin sitecustomize must not re-dial the dead
        # tunnel from inside the fallback
        PYTHONPATH="", PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
        BENCH_MODEL=os.environ.get("BENCH_CPU_MODEL", "gpt-mini"),
        BENCH_SEQ=os.environ.get("BENCH_CPU_SEQ", "256"),
        BENCH_BATCHES=os.environ.get("BENCH_CPU_BATCHES", "8,4"),
    )
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--inner"],
            capture_output=True, text=True, timeout=BENCH_TIMEOUT_S, env=env,
        )
    except subprocess.TimeoutExpired:
        return None
    sys.stderr.write(proc.stderr)
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            record = json.loads(line)
        except ValueError:
            continue
        record["backend"] = "cpu_fallback"
        record["probe_error"] = probe_error
        return record
    return None


def _probe_backend_with_retry() -> dict:
    """Retry the bounded probe: the TPU tunnel here is documented to flap
    for stretches (BASELINE.md round 2 — down ~4 h at end-of-round bench
    time), and a transient outage must not turn into a null round record
    when one more attempt a minute later would have answered."""
    last: dict = {"error": "no probe attempts made"}
    for attempt in range(1, PROBE_ATTEMPTS + 1):
        last = _probe_backend()
        if "error" not in last:
            return last
        print(
            f"probe attempt {attempt}/{PROBE_ATTEMPTS}: {last['error']}",
            file=sys.stderr,
        )
        if not _is_transient(last["error"], last.get("etype")):
            return last  # permanent: retrying can't fix an ImportError
        if attempt < PROBE_ATTEMPTS:
            time.sleep(PROBE_BACKOFF_S)
    return last


def check_throughput_plausible(
    tokens_per_sec: float,
    flops_per_token: float,
    peak_flops: float | None,
    slack: float = 1.2,
) -> None:
    """Honesty guard for the D2H timing workaround (VERDICT r2 weak #5).

    Timing here synchronizes via a real device_get of the last chained
    step's loss because ``block_until_ready`` returns early on this remote
    backend.  If the backend quirk ever extends to ``device_get`` too, the
    measured wall-clock collapses and the reported throughput becomes
    physically impossible.  Refuse to report a number that implies more
    than ``slack``× the chip's peak FLOP rate — fail loudly instead.
    """
    if peak_flops is None or not tokens_per_sec:
        return
    achieved = tokens_per_sec * flops_per_token
    if achieved > slack * peak_flops:
        raise RuntimeError(
            f"implausible throughput: {achieved / 1e12:.1f} TFLOP/s implied "
            f"> {slack}x chip peak {peak_flops / 1e12:.1f} TFLOP/s — the "
            "D2H sync is not actually synchronizing on this backend; "
            "refusing to report inflated numbers"
        )


def check_decode_plausible(
    decode_tokens_per_sec: float,
    batch: int,
    param_bytes: float,
    peak_hbm_bytes: float | None,
    slack: float = 1.5,
) -> None:
    """Roofline honesty guard for the decode extra (VERDICT r3 next #8).

    KV-cached decode is memory-bound: every decode step streams the full
    parameter set from HBM once (shared across the batch), so steps/sec
    cannot exceed bandwidth / param-bytes.  The differential D2H timing
    the decode extra uses is exposed to the same backend sync quirk as the
    train-step path; refuse a rate that implies more than ``slack``× the
    chip's HBM bandwidth rather than report it.
    """
    if peak_hbm_bytes is None or not decode_tokens_per_sec:
        return
    required = (decode_tokens_per_sec / batch) * param_bytes
    if required > slack * peak_hbm_bytes:
        raise RuntimeError(
            f"implausible decode rate: {decode_tokens_per_sec:.0f} tok/s at "
            f"batch {batch} implies {required / 1e9:.0f} GB/s of parameter "
            f"streaming > {slack}x chip HBM bandwidth "
            f"{peak_hbm_bytes / 1e9:.0f} GB/s — timing did not synchronize"
        )


def profile_inner(outdir: str) -> int:
    """Capture a jax.profiler device trace of the winning train-step config
    (VERDICT r2 next #2): 3 warmup steps, then 5 traced steps. Analyse with
    TensorBoard's profile plugin / Perfetto on the written xplane files."""
    import jax
    import jax.numpy as jnp

    from mingpt_distributed_tpu.config import GPTConfig, OptimizerConfig
    from mingpt_distributed_tpu.models import gpt
    from mingpt_distributed_tpu.training.optimizer import make_optimizer
    from mingpt_distributed_tpu.training.trainer import make_train_step

    model = os.environ.get("BENCH_MODEL", "gpt2")
    seq = int(os.environ.get("BENCH_SEQ", "1024"))
    batch = int(os.environ.get("BENCH_PROFILE_BATCH", "16"))
    attention = os.environ.get("BENCH_PROFILE_ATTENTION", "flash")
    # default to the round-4 winning step config (unrolled layer loop)
    unroll_layers = os.environ.get("BENCH_PROFILE_UNROLL", "1") == "1"
    remat = os.environ.get("BENCH_PROFILE_REMAT", "0") == "1"
    cfg = GPTConfig.make(
        model_type=model,
        embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0,
        dtype="bfloat16", attention=attention,
        unroll_layers=unroll_layers, remat=remat,
        block_size=max(seq, 1024),
    )
    optimizer = make_optimizer(OptimizerConfig(), grad_norm_clip=1.0)
    step_fn = jax.jit(make_train_step(cfg, optimizer), donate_argnums=(0,))
    state = jax.jit(
        lambda k: {
            "params": gpt.init(k, cfg),
            "opt_state": optimizer.init(gpt.init(k, cfg)),
            "step": jnp.asarray(0, dtype=jnp.int32),
        }
    )(jax.random.key(0))
    tokens = jax.random.randint(
        jax.random.key(1), (batch, seq), 0, cfg.vocab_size, dtype=jnp.int32
    )
    rng = jax.random.key(2)
    for _ in range(3):
        state, m = step_fn(state, (tokens, tokens), rng)
    float(jax.device_get(m["loss"]))
    n = 5
    with jax.profiler.trace(outdir):
        t0 = time.perf_counter()
        for _ in range(n):
            state, m = step_fn(state, (tokens, tokens), rng)
        loss = float(jax.device_get(m["loss"]))
        dt = time.perf_counter() - t0
    print(json.dumps({
        "profile_dir": outdir, "batch": batch, "seq": seq,
        "attention": attention, "steps": n,
        "unroll_layers": unroll_layers, "remat": remat,
        "steps_per_sec": round(n / dt, 3), "loss": loss,
        "device": jax.devices()[0].device_kind,
    }))
    return 0


def _attach_multichip(record: dict) -> None:
    """ZeRO dp update-sharding extra (ISSUE 9) plus the tensor-parallel
    sharded-serving block (ISSUE 14): per-device param/opt-state bytes
    and update-phase time, replicated vs ``zero_dp``, and per-device
    KV-pool bytes + decode/prefill time at tp=1 vs tp=2 — all measured
    on hermetic virtual-CPU meshes in one bounded subprocess. Never
    fatal, and independent of the accelerator probe (the meshes are
    host-platform by construction), so it also lands on cpu_fallback
    records."""
    try:
        if os.environ.get("BENCH_MULTICHIP", "1") == "0":
            raise RuntimeError("disabled via BENCH_MULTICHIP=0")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = ""
        env.pop("PALLAS_AXON_POOL_IPS", None)
        flags = [
            f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append("--xla_force_host_platform_device_count=8")
        env["XLA_FLAGS"] = " ".join(flags)
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--multichip-inner"],
            capture_output=True, text=True, env=env,
            timeout=_env_num("BENCH_MULTICHIP_TIMEOUT_S", 600, int),
        )
        sys.stderr.write(proc.stderr)
        for line in reversed(proc.stdout.strip().splitlines()):
            try:
                record["multichip"] = json.loads(line)
                return
            except ValueError:
                continue
        raise RuntimeError(f"rc={proc.returncode}, no JSON line")
    except Exception as e:  # noqa: BLE001 — optional extra, never fatal
        print(f"multichip extra skipped: {e}", file=sys.stderr)


def main() -> int:
    probe = _probe_backend_with_retry()
    if "error" in probe:
        # dead accelerator: record a real (labelled) CPU number rather
        # than yet another null round artifact
        print(f"probe failed ({probe['error']}); falling back to a "
              "smaller-geometry CPU measurement", file=sys.stderr)
        record = _cpu_fallback_record(probe["error"])
        if record is None:
            record = _error_record(probe["error"])
        _attach_multichip(record)
        print(json.dumps(record))
        return 0
    if "--profile" in sys.argv:
        i = sys.argv.index("--profile")
        outdir = (
            sys.argv[i + 1]
            if len(sys.argv) > i + 1 and not sys.argv[i + 1].startswith("-")
            else "profile_trace"
        )
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--profile-inner", outdir],
                capture_output=True,
                text=True,
                timeout=BENCH_TIMEOUT_S,
            )
        except subprocess.TimeoutExpired as e:
            # salvage a completed record from partial stdout (same recovery
            # as the inner-mode handler): the trace may hang AFTER the
            # measurement line was printed
            partial = e.stdout or b""
            if isinstance(partial, bytes):
                partial = partial.decode(errors="replace")
            for line in reversed(partial.strip().splitlines()):
                try:
                    print(json.dumps(json.loads(line)))
                    return 0
                except ValueError:
                    continue
            print(json.dumps(_error_record(
                f"profile run timed out after {BENCH_TIMEOUT_S}s")))
            return 0
        sys.stderr.write(proc.stderr)
        for line in reversed(proc.stdout.strip().splitlines()):
            try:
                print(json.dumps(json.loads(line)))
                return 0
            except ValueError:
                continue
        tail = (proc.stderr or "").strip().splitlines()[-3:]
        print(json.dumps(_error_record(
            f"profile rc={proc.returncode}, no JSON: " + " | ".join(tail))))
        return 0
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--inner"],
            capture_output=True,
            text=True,
            timeout=BENCH_TIMEOUT_S,
        )
    except subprocess.TimeoutExpired as e:
        # the inner process emits the headline record as soon as the main
        # sweep finishes (before optional extras) — recover it from the
        # partial stdout rather than discarding a completed measurement
        partial = e.stdout or b""
        if isinstance(partial, bytes):
            partial = partial.decode(errors="replace")
        for line in reversed(partial.strip().splitlines()):
            try:
                print(json.dumps(json.loads(line)))
                return 0
            except ValueError:
                continue
        print(json.dumps(_error_record(
            f"bench timed out after {BENCH_TIMEOUT_S}s "
            f"(backend {probe.get('kind')})")))
        return 0
    sys.stderr.write(proc.stderr)
    record = None
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            record = json.loads(line)
            break
        except ValueError:
            continue
    if record is None:
        tail = (proc.stderr or "").strip().splitlines()[-3:]
        record = _error_record(
            f"bench rc={proc.returncode}, no JSON: " + " | ".join(tail))
    _attach_multichip(record)
    print(json.dumps(record))
    return 0


def inner() -> int:
    import jax
    import jax.numpy as jnp

    from mingpt_distributed_tpu.config import GPTConfig, OptimizerConfig
    from mingpt_distributed_tpu.models import gpt
    from mingpt_distributed_tpu.telemetry import (
        peak_flops_per_chip,
        peak_hbm_bytes_per_chip,
    )
    from mingpt_distributed_tpu.training.metrics import flops_per_token
    from mingpt_distributed_tpu.training.optimizer import make_optimizer
    from mingpt_distributed_tpu.training.trainer import make_train_step

    # env overrides exist so the end-to-end bench contract (one JSON line,
    # metric/value/unit/vs_baseline keys) is testable on CPU with a tiny
    # model; the driver's real run uses the defaults
    model = os.environ.get("BENCH_MODEL", "gpt2")
    seq = int(os.environ.get("BENCH_SEQ", "1024"))
    default_batches = tuple(
        int(b)
        for b in os.environ.get("BENCH_BATCHES", "64,32,16,8,4").split(",")
    )

    def bench_attention(
        attention: str, batches=default_batches, scan_unroll: int = 1,
        remat: bool = False, unroll_layers: bool = False,
        loss_chunks: int = 8,
    ) -> tuple[int, float] | None:
        """(batch, steps/sec) at the largest batch that fits, else None."""
        cfg = GPTConfig.make(
            model_type=model,
            embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0,
            dtype="bfloat16",
            attention=attention,
            scan_unroll=scan_unroll,
            remat=remat,
            unroll_layers=unroll_layers,
            loss_chunks=loss_chunks,
            block_size=max(seq, 1024),
        )
        optimizer = make_optimizer(OptimizerConfig(), grad_norm_clip=1.0)
        step_fn = jax.jit(make_train_step(cfg, optimizer), donate_argnums=(0,))

        def try_batch(batch: int) -> float:
            state = jax.jit(
                lambda k: {
                    "params": gpt.init(k, cfg),
                    "opt_state": optimizer.init(gpt.init(k, cfg)),
                    "step": jnp.asarray(0, dtype=jnp.int32),
                }
            )(jax.random.key(0))
            tokens = jax.random.randint(
                jax.random.key(1), (batch, seq), 0, cfg.vocab_size,
                dtype=jnp.int32,
            )
            rng = jax.random.key(2)

            def fetch(m) -> float:
                # an actual D2H value fetch, not block_until_ready: on some
                # remote backends block_until_ready returns before execution
                # finishes, which inflates steps/sec by orders of magnitude
                return float(jax.device_get(m["loss"]))

            for _ in range(3):  # compile + warmup
                state, m = step_fn(state, (tokens, tokens), rng)
            fetch(m)
            n_steps = 20
            t0 = time.perf_counter()
            for _ in range(n_steps):
                state, m = step_fn(state, (tokens, tokens), rng)
            # steps chain through the donated state, so syncing on the last
            # step's metrics bounds the whole loop
            loss = fetch(m)
            dt = time.perf_counter() - t0
            assert loss == loss, "NaN loss in bench"
            return n_steps / dt

        # retry smaller on ANY failure: HBM OOM can surface as an opaque
        # compile error depending on the backend, not just RESOURCE_EXHAUSTED
        for batch in batches:
            try:
                return batch, try_batch(batch)
            except Exception as e:  # noqa: BLE001
                msg = str(e).splitlines()[0] if str(e) else type(e).__name__
                print(f"{attention} batch={batch} failed: {msg}",
                      file=sys.stderr)
                continue
        return None

    results: dict[str, tuple[int, float]] = {}
    unrolls: dict[str, int] = {}
    remats: dict[str, bool] = {}
    layer_unrolls: dict[str, bool] = {}
    ce_chunks: dict[str, int] = {}  # loss_chunks per path (reproducibility)
    # config ladder per attention path, best-first (round-4 on-chip
    # evidence): the unrolled layer loop removes the scan's
    # dynamic-update-slice activation stacking — ~23% of step time on the
    # r4 trace AND the allocation that made batch >= 16 fail to compile —
    # so it both wins on speed (MFU 0.33 -> 0.43) and unlocks larger
    # batches. Scan + remat remains the memory-floor fallback.
    config_ladder = (
        {"unroll_layers": True, "remat": False},
        {"unroll_layers": False, "remat": False},
        {"unroll_layers": False, "remat": True},
    )
    for attention in ("flash", "einsum"):
        r = None
        for knobs in config_ladder:
            r = bench_attention(attention, **knobs)
            if r is not None:
                remats[attention] = knobs["remat"]
                layer_unrolls[attention] = knobs["unroll_layers"]
                break
        if r is not None:
            results[attention] = r
            unrolls[attention] = 1
            ce_chunks[attention] = 8
            print(
                f"{attention}: batch={r[0]} steps/sec={r[1]:.3f}"
                + (" (remat)" if remats[attention] else "")
                + (" (unrolled)" if layer_unrolls[attention] else ""),
                file=sys.stderr,
            )

    flash_block = None  # None = the kernel's default ladder choice
    # record the layout actually taken: the native-(B,T,D) path only
    # applies when the (h, hd) combination packs to 128 lanes (gpt2 12x64
    # does; e.g. gpt2-xl's 25 heads can't pair) — claiming "btd" for a
    # model that routed to the transpose path would misreport the artifact
    _pcfg = GPTConfig.make(model_type=model)
    from mingpt_distributed_tpu.ops import flash_attention as _fa

    flash_layout = (
        "btd"
        if (_fa._btd_applies(_pcfg.n_head, _pcfg.head_dim)
            and os.environ.get("FLASH_LAYOUT", "auto") != "bh")
        else "bh"
    )
    # honor an ambient FLASH_FUSED_BWD=1 (then the whole ladder measures
    # fused and the probe below is skipped) — the record must describe
    # how the headline was actually measured. The flag only acts on the
    # btd path, so it is only recorded there.
    flash_fused_bwd = (flash_layout == "btd"
                       and os.environ.get("FLASH_FUSED_BWD") == "1")
    def try_probe(label, fn):
        """Run an optional tuning probe; a raising probe is logged and
        treated as a miss rather than aborting the bench and losing every
        collected record (ADVICE r5 — bench_attention returning None is the
        expected miss path, but nothing above guarantees it can't raise)."""
        try:
            return fn()
        except Exception as e:  # noqa: BLE001
            msg = str(e).splitlines()[0] if str(e) else type(e).__name__
            print(f"{label} probe raised (skipped): {msg}", file=sys.stderr)
            return None

    def fused_bwd_spot_check() -> bool:
        """Numeric gate for FLASH_FUSED_BWD (ADVICE r5): compile-and-win is
        not parity. Compare the fused dq/dk/dv against the two-kernel
        reference backward on a small btd-path shape; only a match keeps
        the flag. Runs with FLASH_FUSED_BWD=1 already in the env (the
        caller set it); the reference pass flips it off and restores."""
        import numpy as np

        from mingpt_distributed_tpu.ops import flash_attention as fa

        b, t, h, hd = 2, 256, 4, 64
        block = fa._block_sizes(t)
        if block is None or not fa._btd_applies(h, hd):
            print("fused_bwd spot-check shape can't take the btd path; "
                  "refusing the flag", file=sys.stderr)
            return False
        kq, kk, kv, kw = jax.random.split(jax.random.key(0), 4)
        q = jax.random.normal(kq, (b, t, h * hd), jnp.bfloat16)
        k = jax.random.normal(kk, (b, t, h * hd), jnp.bfloat16)
        v = jax.random.normal(kv, (b, t, h * hd), jnp.bfloat16)
        w = jax.random.normal(kw, (b, t, h * hd), jnp.bfloat16)
        scale = 1.0 / (hd ** 0.5)

        def loss(q, k, v):
            out = fa._flash_btd(q, k, v, h, scale, block, None, None)
            return jnp.sum(out.astype(jnp.float32) * w.astype(jnp.float32))

        grad_fn = jax.grad(loss, argnums=(0, 1, 2))
        fused = jax.device_get(grad_fn(q, k, v))
        os.environ["FLASH_FUSED_BWD"] = "0"
        try:
            ref = jax.device_get(grad_fn(q, k, v))
        finally:
            os.environ["FLASH_FUSED_BWD"] = "1"
        for name, gf, gr in zip(("dq", "dk", "dv"), fused, ref):
            gf = np.asarray(gf, np.float32)
            gr = np.asarray(gr, np.float32)
            # both paths accumulate in f32 and emit bf16: anything beyond
            # a few ulps of bf16 on the largest gradient is a real bug
            tol = 3e-2 * max(1.0, float(np.abs(gr).max()))
            err = float(np.abs(gf - gr).max())
            if not np.isfinite(err) or err > tol:
                print(f"fused_bwd spot-check FAILED on {name}: "
                      f"max|Δ|={err:.3e} tol={tol:.3e}", file=sys.stderr)
                return False
        return True

    if "flash" in results:
        # one bounded extra compile: layer-scan unroll at the winning batch
        # (lets XLA fuse across layer boundaries); only meaningful when the
        # scan path won (the unrolled python loop has no scan to unroll)
        b_star, sps_star = results["flash"]
        if not layer_unrolls["flash"]:
            r = try_probe("unroll", lambda: bench_attention(
                "flash", batches=(b_star,), scan_unroll=4,
                remat=remats["flash"]))
            if r is not None and r[1] > sps_star:
                results["flash"] = r
                unrolls["flash"] = 4
                print(f"flash unroll=4: steps/sec={r[1]:.3f} (kept)",
                      file=sys.stderr)
        # flash block-size sweep at the winning batch (VERDICT r2 weak #4:
        # the (512, 256, 128) ladder was never measured) — two bounded
        # extra compiles; keep the override only if it beats the default
        for blk in (256, 128):
            os.environ["FLASH_BLOCK"] = str(blk)
            try:
                r = try_probe(f"block={blk}", lambda: bench_attention(
                    "flash", batches=(results["flash"][0],),
                    scan_unroll=unrolls["flash"], remat=remats["flash"],
                    unroll_layers=layer_unrolls["flash"],
                ))
            finally:
                os.environ.pop("FLASH_BLOCK", None)
            if r is not None and r[1] > results["flash"][1]:
                results["flash"] = r
                flash_block = blk
                print(f"flash block={blk}: steps/sec={r[1]:.3f} (kept)",
                      file=sys.stderr)
        if flash_block is not None:
            os.environ["FLASH_BLOCK"] = str(flash_block)  # for extras below
        # CE chunk-count probe (r4 on-chip: 4 beat 8 by ~1% at batch 16 with
        # the unrolled chunk loop; larger counts lose matmul efficiency) —
        # one bounded extra compile, kept only if faster
        r = try_probe("loss_chunks=4", lambda: bench_attention(
            "flash", batches=(results["flash"][0],),
            scan_unroll=unrolls["flash"], remat=remats["flash"],
            unroll_layers=layer_unrolls["flash"], loss_chunks=4,
        ))
        if r is not None and r[1] > results["flash"][1]:
            results["flash"] = r
            ce_chunks["flash"] = 4
            print(f"flash loss_chunks=4: steps/sec={r[1]:.3f} (kept)",
                  file=sys.stderr)
        # layout probe: the native-(B,T,D) kernels are the default (r5:
        # +10% at b32 on a v5e); one bounded compile checks the transpose
        # path hasn't overtaken it on THIS backend, and the record carries
        # the winner either way. Skipped when the model can't take the btd
        # path at all (probe would compare the transpose path to itself).
        if flash_layout == "btd":
            prior_layout = os.environ.get("FLASH_LAYOUT")
            os.environ["FLASH_LAYOUT"] = "bh"
            try:
                r = try_probe("layout=bh", lambda: bench_attention(
                    "flash", batches=(results["flash"][0],),
                    scan_unroll=unrolls["flash"], remat=remats["flash"],
                    unroll_layers=layer_unrolls["flash"],
                    loss_chunks=ce_chunks["flash"],
                ))
            finally:
                if prior_layout is None:
                    os.environ.pop("FLASH_LAYOUT", None)
                else:
                    os.environ["FLASH_LAYOUT"] = prior_layout
            if r is not None and r[1] > results["flash"][1]:
                results["flash"] = r
                flash_layout = "bh"
                # the kept measurement never ran the fused kernel (it
                # only exists on the btd path) — don't record it
                flash_fused_bwd = False
                os.environ["FLASH_LAYOUT"] = "bh"  # for extras below
                print(f"flash layout=bh: steps/sec={r[1]:.3f} (kept)",
                      file=sys.stderr)
        # fused-backward probe: the dq+dk+dv single-pass kernel is opt-in
        # until chip-validated (interpret-mode parity only — see
        # _flash_bwd_btd's gate note); one bounded compile turns it on
        # only when it compiles, WINS on this backend, and passes the
        # numeric spot-check against the reference backward. The keep
        # decision runs after the probe (never inside a finally:, ADVICE
        # r5 — a raising probe used to mutate results during exception
        # unwind and then abort the whole bench); the env flag ends set
        # iff the kernel is kept.
        if flash_layout == "btd" and not flash_fused_bwd:
            os.environ["FLASH_FUSED_BWD"] = "1"
            r = try_probe("fused_bwd", lambda: bench_attention(
                "flash", batches=(results["flash"][0],),
                scan_unroll=unrolls["flash"], remat=remats["flash"],
                unroll_layers=layer_unrolls["flash"],
                loss_chunks=ce_chunks["flash"],
            ))
            keep_fused = r is not None and r[1] > results["flash"][1]
            if keep_fused and not try_probe("fused_bwd numeric",
                                            fused_bwd_spot_check):
                print("flash fused_bwd: won on speed but failed the "
                      "numeric spot-check; discarding", file=sys.stderr)
                keep_fused = False
            if keep_fused:
                results["flash"] = r
                flash_fused_bwd = True
                print(f"flash fused_bwd: steps/sec={r[1]:.3f} (kept)",
                      file=sys.stderr)
            else:
                os.environ.pop("FLASH_FUSED_BWD", None)

    if not results:
        print(json.dumps(_error_record("all attention paths failed or OOMed")))
        return 0

    cfg = GPTConfig.make(model_type=model)
    fpt = flops_per_token(cfg, seq)
    peak = peak_flops_per_chip()
    peak_source = "chip_table" if peak else None
    if peak is None and jax.default_backend() == "cpu":
        # no table entry for CPUs: measure an achievable matmul FLOP rate
        # so the cpu-fallback path can still report a real MFU-style
        # fraction (clearly labelled — it is a proxy denominator, not a
        # chip spec)
        n = 1024
        a = jax.random.normal(jax.random.key(0), (n, n), jnp.float32)
        mm = jax.jit(lambda a: a @ a)
        mm(a).block_until_ready()
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            mm(a).block_until_ready()
            best = max(best, 2.0 * n ** 3 / (time.perf_counter() - t0))
        peak = best
        peak_source = "measured_cpu_matmul"

    def mfu_of(batch: int, sps: float) -> tuple[float, float | None]:
        tps = sps * batch * seq
        return tps, (tps * fpt / peak if peak else None)

    # plausibility-gate EVERY path, not just the eventual headline (ADVICE
    # r3): an implausible per-path record is as dishonest in the artifact
    # as an implausible headline
    per_path = {}
    for attention in list(results):
        batch, sps = results[attention]
        tps, mfu = mfu_of(batch, sps)
        try:
            check_throughput_plausible(tps, fpt, peak)
        except RuntimeError as e:
            print(f"{attention} path refused: {e}", file=sys.stderr)
            del results[attention]
            if attention == "flash":
                # the sweep's winning block was measured by a refused
                # timing — don't report it or let it steer the extras
                flash_block = None
                os.environ.pop("FLASH_BLOCK", None)
            continue
        per_path[attention] = {
            "batch": batch,
            "tokens_per_sec_per_chip": round(tps, 1),
            "mfu": round(mfu, 4) if mfu is not None else None,
            "scan_unroll": unrolls.get(attention, 1),
            "remat": remats.get(attention, False),
            "unroll_layers": layer_unrolls.get(attention, False),
            "loss_chunks": ce_chunks.get(attention, 8),
            # the scan_unroll / FLASH_BLOCK / loss_chunks probes run for the
            # flash path only (ADVICE r4): non-flash records carry the
            # defaults and are slightly understated
            "tuned": attention == "flash",
        }
    if not results:
        print(json.dumps(_error_record(
            "every attention path implied > 1.2x chip peak — the D2H sync "
            "is not synchronizing on this backend; refusing to report")))
        return 0

    best = max(
        results,
        key=lambda a: per_path[a]["mfu"] or per_path[a]["tokens_per_sec_per_chip"],
    )
    batch, sps = results[best]
    tokens_per_sec, mfu = mfu_of(batch, sps)

    def emit(long_ctx):
        dev = jax.devices()[0]
        record = {
            "metric": METRIC,
            "value": round(mfu, 4) if mfu is not None else None,
            "unit": "fraction",
            # north-star target is 0.80 MFU (BASELINE.md) — no reference-
            # published number exists, so the baseline is the target
            "vs_baseline": round(mfu / 0.80, 4) if mfu is not None else None,
            "attention": best,
            "scan_unroll": unrolls.get(best, 1),
            "unroll_layers": layer_unrolls.get(best, False),
            "loss_chunks": ce_chunks.get(best, 8),
            "flash_block": flash_block,  # None = default ladder
            "flash_layout": flash_layout if best == "flash" else None,
            "flash_fused_bwd": flash_fused_bwd if best == "flash" else None,
            "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
            "flops_per_token": fpt,
            "achieved_tflops": round(tokens_per_sec * fpt / 1e12, 2),
            "peak_tflops": round(peak / 1e12, 1) if peak else None,
            "peak_source": peak_source,
            "batch": batch,
            "seq": seq,
            "device": dev.device_kind,
            "n_devices": jax.device_count(),
            "paths": per_path,
            "long_context": long_ctx,
            "decode": decode,  # KV-cached greedy decode extra (TPU only)
            "serving": serving,  # continuous-batching admission probe
        }
        print(json.dumps(record), flush=True)

    # headline record FIRST: if the optional extras below hang or die, the
    # outer process parses the last complete JSON line and the
    # already-measured MFU is never lost
    decode = None
    serving = None
    emit(None)

    # long-context line (SURVEY §5.7): one bounded flash fwd+bwd at T=8192 —
    # the kernel's O(block) VMEM story, measured whenever a chip is up
    long_ctx = None
    try:
        if jax.default_backend() != "tpu":
            raise RuntimeError("long-context extra is TPU-only (interpret "
                               "mode at T=8192 would dominate the bench)")
        import math as _math

        bh, t_lc, hd = 8, 8192, 128
        ks = jax.random.split(jax.random.key(3), 3)
        q = jax.random.normal(ks[0], (bh, t_lc, hd), jnp.bfloat16)
        k = jax.random.normal(ks[1], (bh, t_lc, hd), jnp.bfloat16)
        v = jax.random.normal(ks[2], (bh, t_lc, hd), jnp.bfloat16)

        from mingpt_distributed_tpu.ops import flash_attention as fa

        def attn_loss(q, k, v):
            out = fa.flash_with_lse(q, k, v, 1.0 / _math.sqrt(hd), 512, True)[0]
            return jnp.sum(out.astype(jnp.float32) ** 2)

        def timed_min(gfn, n=5, repeats=5):
            """Min + spread over >= 5 timed windows: independent dispatches
            through the tunnel relay don't pipeline, so single windows are
            noisy (r4: 2.01x and 0.76x window_speedup on identical code the
            same day). The min is the estimator; the per-trial list is
            recorded so the artifact carries the variance, and the speedup
            is only cited when the spread supports it (VERDICT r4 #8)."""
            for _ in range(2):
                r = gfn(q, k, v)
            float(jax.device_get(r[0][0, 0, 0]))
            trials = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                for _ in range(n):
                    r = gfn(q, k, v)
                float(jax.device_get(r[0][0, 0, 0]))
                trials.append((time.perf_counter() - t0) / n)
            return min(trials), trials

        g = jax.jit(jax.grad(attn_loss, argnums=(0, 1, 2)))
        dt, dt_trials = timed_min(g)
        # causal fwd 2 matmuls: 4*bh*T^2*hd/2 flops; bwd ~2.5x more
        flops = 3.5 * 4 * bh * t_lc * t_lc * hd / 2
        if peak and flops / dt > 1.2 * peak:
            raise RuntimeError(
                f"implausible long-context timing: {flops / dt / 1e12:.0f} "
                f"TFLOP/s > 1.2x peak {peak / 1e12:.0f}")
        long_ctx = {
            "seq": t_lc, "ms_per_iter": round(dt * 1e3, 2),
            "ms_trials": [round(t * 1e3, 2) for t in dt_trials],
            "attn_tflops": round(flops / dt / 1e12, 1),
        }

        # banded variant at the same shapes: the sliding-window kernel
        # skips out-of-band blocks, so wall-clock should scale ~window/T
        win = 1024

        def attn_loss_win(q, k, v):
            # keyword args: _flash's positional nondiff layout has already
            # changed once (softcap appended) — don't depend on it
            out = fa._flash(q, k, v, 1.0 / _math.sqrt(hd), 512, window=win)
            return jnp.sum(out.astype(jnp.float32) ** 2)

        gw = jax.jit(jax.grad(attn_loss_win, argnums=(0, 1, 2)))
        dt_w, dt_w_trials = timed_min(gw)
        # banded rows attend ~window keys vs the causal average T/2, so
        # banded work ~= full * 2*win/T; same 1.2x-peak refusal applies
        flops_w = flops * 2 * win / t_lc
        if peak and flops_w / dt_w > 1.2 * peak:
            print(f"banded extra refused: {flops_w / dt_w / 1e12:.0f} "
                  f"TFLOP/s implied > 1.2x peak", file=sys.stderr)
        else:
            long_ctx["window"] = win
            long_ctx["window_ms_per_iter"] = round(dt_w * 1e3, 2)
            long_ctx["window_ms_trials"] = [
                round(t * 1e3, 2) for t in dt_w_trials
            ]
            # cite the speedup only when the spread supports it: if either
            # set's trials vary more than the claimed effect, the number is
            # relay noise, not a measurement (r4: 2.01x and 0.76x on
            # identical code)
            spread = max(
                (max(ts) - min(ts)) / min(ts)
                for ts in (dt_trials, dt_w_trials)
            )
            long_ctx["trial_spread"] = round(spread, 3)
            speedup = dt / dt_w
            if abs(speedup - 1.0) > spread:
                long_ctx["window_speedup"] = round(speedup, 2)
            else:
                long_ctx["window_speedup_unstable"] = round(speedup, 2)
    except Exception as e:  # noqa: BLE001 — optional extra, never fatal
        print(f"long-context extra skipped: {e}", file=sys.stderr)

    # decode throughput extra — LAST, so a slow compile here can't starve
    # the longer-standing long-context metric out of the record (SURVEY C9:
    # the reference re-forwards the whole sequence per token; the KV-cached
    # compiled decode is a capability worth a number). The rate is the
    # DIFFERENTIAL between two generation lengths, so the shared prefill
    # forward cancels and pure decode-step throughput is reported.
    try:
        if jax.default_backend() != "tpu":
            raise RuntimeError("decode extra is TPU-only")
        from mingpt_distributed_tpu.models import generate as gen_mod

        dec_cfg = GPTConfig.make(
            model_type=model,
            embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0,
            dtype="bfloat16", block_size=max(seq, 1024),
        )
        dec_params = jax.jit(lambda k: gpt.init(k, dec_cfg))(jax.random.key(4))
        db, prompt_len = 8, 128
        n_short, n_long = 256, 512
        prompt = jax.random.randint(
            jax.random.key(5), (db, prompt_len), 0, dec_cfg.vocab_size,
            dtype=jnp.int32,
        )

        def timed(n):
            out = gen_mod.generate(dec_params, dec_cfg, prompt, n)
            int(jax.device_get(out[0, -1]))  # compile + sync
            t0 = time.perf_counter()
            out = gen_mod.generate(dec_params, dec_cfg, prompt, n)
            int(jax.device_get(out[0, -1]))
            return time.perf_counter() - t0

        dt_short, dt_long = timed(n_short), timed(n_long)
        if dt_long > dt_short:
            dtps = db * (n_long - n_short) / (dt_long - dt_short)
            # bf16 compute copy of the params is the floor of per-step HBM
            # traffic (KV-cache reads come on top — bound is conservative)
            check_decode_plausible(
                dtps, db, 2 * gpt.param_count(dec_params),
                peak_hbm_bytes_per_chip())
            decode = {
                "batch": db, "prompt_len": prompt_len,
                "new_tokens": n_long,
                "decode_tokens_per_sec": round(dtps, 1),
            }
    except Exception as e:  # noqa: BLE001 — optional extra, never fatal
        print(f"decode extra skipped: {e}", file=sys.stderr)

    # serving-throughput extra (ISSUE 3): the continuous-batching server
    # under a mixed short/long prompt trace with bucketed + chunked prefill
    # and the shared-prefix store on. Records tokens/sec and — the
    # acceptance evidence — per-admission cost scaling: a short prompt's
    # compiled prefill is measurably cheaper than a full-window one, and a
    # prefix-cache hit pays only its tail. A tiny model keeps the extra
    # bounded on every backend (the numbers compare prefill geometries to
    # EACH OTHER, which a tiny model preserves).
    try:
        if os.environ.get("BENCH_SERVING", "1") == "0":
            raise RuntimeError("disabled via BENCH_SERVING=0")
        serving = serving_probe()
        print(f"serving extra: {json.dumps(serving)}", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 — optional extra, never fatal
        print(f"serving extra skipped: {e}", file=sys.stderr)

    if long_ctx is not None or decode is not None or serving is not None:
        emit(long_ctx)  # augmented record supersedes the headline-only one
    return 0


#: generous-by-design objectives for the serving probe: the point of the
#: BENCH block is recording *observed* exact-quantile latencies and the
#: attainment grade over rounds, not gating CI on a tiny-model number
SERVING_SLO_SPEC = "ttft_p99<=2.0,itl_p99<=0.5,shed_rate<=0.0"


def serving_probe() -> dict:
    """Continuous-batching admission/throughput probe on a tiny model.

    Trace: 24 requests, cycling long (100-token) / shared-prefix (48-token
    system prompt + 8) / short (12-token) prompts through 4 slots with a
    (16, 32, 64, 128) bucket ladder, 32-token chunks and the prefix store
    enabled. Also times the compiled prefill at three admission
    geometries after warmup — short bucket, full window, prefix-hit tail
    — which is the prompt-length-proportional-cost claim in one place.

    The run is traced end-to-end (ISSUE 10): a TraceRecorder collects
    per-request timelines and the returned record carries an ``slo``
    block — exact-quantile TTFT/ITL/shed objectives graded by
    telemetry.slo — so BENCH rounds record SLO attainment alongside
    throughput.

    ISSUE 11: a ``speculative`` block replays the same trace through a
    draft/verify server (draft = target weights, accept rate 1.0) and
    records tokens/sec vs the plain path, tokens-per-verify and the
    verify-executable count — cpu_fallback compatible, token-exactness
    asserted against the non-spec handles.
    """
    import jax
    import numpy as np

    from mingpt_distributed_tpu import telemetry
    from mingpt_distributed_tpu.config import GPTConfig
    from mingpt_distributed_tpu.models import gpt
    from mingpt_distributed_tpu.serving import InferenceServer, Request

    cfg = GPTConfig.make(
        n_layer=2, n_head=2, n_embd=64, vocab_size=256, block_size=128,
        embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0, dtype="float32",
    )
    params = gpt.init(jax.random.key(0), cfg)
    recorder = telemetry.TraceRecorder(sample=1.0)
    server = InferenceServer(
        params, cfg, n_slots=4, prefill_buckets=(16, 32, 64, 128),
        prefill_chunk=32, prefix_cache_mb=16.0, warmup=True,
        trace_recorder=recorder,
    )
    rng = np.random.RandomState(0)
    shared = rng.randint(0, cfg.vocab_size, 48).tolist()
    prompts = []
    for i in range(24):
        if i % 3 == 0:
            prompt = rng.randint(0, cfg.vocab_size, 100).tolist()
        elif i % 3 == 1:
            prompt = shared + rng.randint(0, cfg.vocab_size, 8).tolist()
        else:
            prompt = rng.randint(0, cfg.vocab_size, 12).tolist()
        prompts.append(prompt)
    reqs = [Request(prompt=p, max_new_tokens=16) for p in prompts]
    t0 = time.perf_counter()
    handles = server.generate_batch(reqs)
    wall = time.perf_counter() - t0
    m = server.summary()
    assert all(h.finished for h in handles)

    # speculative block (ISSUE 11): the SAME 24-request trace through a
    # second server with draft/verify decoding. Draft = the target's own
    # weights, so acceptance is deterministic (rate 1.0) on every backend
    # — the block measures the propose→verify machinery's throughput
    # against the plain path, not draft quality. Token-exactness is
    # asserted request-by-request against the non-spec run.
    spec_k = 3
    spec_server = InferenceServer(
        params, cfg, n_slots=4, prefill_buckets=(16, 32, 64, 128),
        prefill_chunk=32, prefix_cache_mb=16.0, warmup=True,
        draft_params=params, draft_cfg=cfg, spec_k=spec_k,
    )
    spec_reqs = [Request(prompt=p, max_new_tokens=16) for p in prompts]
    t0 = time.perf_counter()
    spec_handles = spec_server.generate_batch(spec_reqs)
    spec_wall = time.perf_counter() - t0
    sm = spec_server.metrics
    assert [h.tokens for h in spec_handles] == [h.tokens for h in handles], \
        "speculative decode diverged from the plain greedy path"
    spec_tps = sm.tokens_generated / spec_wall
    speculative = {
        "spec_k": spec_k,
        "tokens_per_sec": round(spec_tps, 1),
        "nonspec_tokens_per_sec": round(m["tokens_generated"] / wall, 1),
        "speedup_vs_nonspec": round(
            spec_tps / (m["tokens_generated"] / wall), 3),
        "accept_rate": round(sm.spec_accept_rate, 3),
        "tokens_per_verify_mean": round(sm.spec_tokens_per_verify_mean, 3),
        "verify_rounds": sm.spec_rounds,
        "verify_executables": spec_server.compile_counts()["verify"],
    }

    eng = server.engine
    key = jax.random.key(1)

    def prefill_ms(n_tokens: int, offset: int = 0) -> float:
        ids = list(range(1, n_tokens + 1))
        t0 = time.perf_counter()
        for _ in range(5):
            eng.prefill_chunk_call(0, ids, offset, 1.0, None, None, False, key)
        return (time.perf_counter() - t0) / 5 * 1e3

    short_ms = prefill_ms(16)            # 16-token prompt, bucket 16
    full_ms = prefill_ms(cfg.block_size)  # full-window prompt
    tail_ms = prefill_ms(16, offset=48)  # what a 48-row prefix hit leaves

    # quantized block (ISSUE 18): an int8 twin of the same engine
    # geometry — bytes-per-slot (payload + fp32 scale planes) against
    # the fp32 pool, max admissible slots under a fixed synthetic
    # per-device HBM budget (the slots-per-chip multiplier headline,
    # asserted strictly higher at int8), and the timed compiled decode
    # step at each dtype. Accuracy lives in serve.py --selftest-quant;
    # this block records the capacity arithmetic perf_diff watches.
    from mingpt_distributed_tpu.serving import quant as quant_lib
    from mingpt_distributed_tpu.serving.engine import DecodeEngine

    q_eng = DecodeEngine(
        params, cfg, n_slots=4, prefill_buckets=(16, 32, 64, 128),
        kv_dtype="int8",
    )

    def decode_step_ms(e) -> float:
        n = e.n_slots
        zeros = np.zeros(n, np.int32)
        step = lambda: e.decode_step(  # noqa: E731
            zeros, zeros, np.ones(n, np.float32), zeros,
            np.ones(n, np.float32), np.zeros(n, bool),
            jax.random.split(jax.random.key(2), n))
        step()  # compile
        t0 = time.perf_counter()
        for _ in range(20):
            step()
        return (time.perf_counter() - t0) / 20 * 1e3

    fp32_slot = sum(
        int(a.nbytes) for a in eng.pool.cache.values()) // eng.n_slots
    q_data, q_scales = quant_lib.split_scales(q_eng.pool.cache)
    int8_slot = (sum(int(a.nbytes) for a in q_data.values())
                 + sum(int(a.nbytes) for a in q_scales.values())
                 ) // q_eng.n_slots
    hbm_budget = 64 * 1024 * 1024  # synthetic per-device KV budget
    max_slots_fp32 = hbm_budget // fp32_slot
    max_slots_int8 = hbm_budget // int8_slot
    assert max_slots_int8 > max_slots_fp32, \
        "int8 KV pool must admit strictly more slots than fp32"
    quantized = {
        "kv_dtype": "int8",
        "bytes_per_slot_fp32": fp32_slot,
        "bytes_per_slot_int8": int8_slot,
        "bytes_ratio": round(int8_slot / fp32_slot, 4),
        "hbm_budget_mb": hbm_budget // (1024 * 1024),
        "max_slots_fp32": max_slots_fp32,
        "max_slots_int8": max_slots_int8,
        "decode_step_fp32_ms": round(decode_step_ms(eng), 3),
        "decode_step_int8_ms": round(decode_step_ms(q_eng), 3),
    }

    slo = telemetry.evaluate_slos(
        recorder.completed_requests(),
        telemetry.parse_slo_spec(SERVING_SLO_SPEC))
    return {
        "tokens_per_sec": round(m["tokens_generated"] / wall, 1),
        "requests": len(reqs),
        "slots": 4,
        "buckets": list(eng.buckets),
        "prefill_chunk": eng.prefill_chunk,
        "prefill_pad_overhead": round(m["prefill_pad_overhead"], 3),
        "prefix_hit_rate": round(m["prefix_hit_rate"], 3),
        "prefix_rows_reused": m["prefix_rows_reused"],
        "admission_stall_mean_ms": round(
            m["admission_stall_mean_s"] * 1e3, 2),
        "prefill_short16_ms": round(short_ms, 2),
        "prefill_full_window_ms": round(full_ms, 2),
        "prefill_prefix_tail_ms": round(tail_ms, 2),
        "short_vs_full_speedup": round(full_ms / short_ms, 2),
        "speculative": speculative,
        "quantized": quantized,
        "slo": slo,
    }


def serving_inner() -> int:
    """``--serving``: the serving probe as a standalone BENCH record —
    one JSON line whose headline is serving throughput and whose
    ``serving.slo`` block is the graded exact-quantile attainment
    report. Runs on any backend (tiny model, CPU included)."""
    serving = serving_probe()
    slo = serving["slo"]
    print(json.dumps({
        "metric": "serving_tokens_per_sec",
        "value": serving["tokens_per_sec"],
        "unit": "tokens/sec",
        "slo_grade": slo["grade"],
        "slo_attainment": slo["attainment"],
        "serving": serving,
    }), flush=True)
    return 0


def traffic_inner() -> int:
    """``--traffic``: the traffic-lab sweep as a standalone BENCH record
    — one JSON line whose headline is the knee rung (first offered-load
    rung where the named SLO objective fails) and whose ``traffic``
    block carries per-policy grades and deadline-hit-rates per rung.
    Runs the canned selftest geometry (tiny model, VirtualClock), so it
    works on any backend and adds nothing to existing records."""
    import traffic as traffic_cli
    from mingpt_distributed_tpu.trafficlab import run_sweep

    cfg, params = traffic_cli._tiny_model()
    spec = traffic_cli.selftest_sweep_spec()
    report = run_sweep(params, cfg, spec, mix=traffic_cli.selftest_mix())
    knee = report["knee"]
    rungs = [
        {
            "rung": rung["rung"],
            "offered_rate": rung["offered_rate"],
            "policies": {
                name: {
                    "grade": cell["slo"]["grade"],
                    "attainment": cell["slo"]["attainment"],
                    "deadline_hit_rate": cell["deadline_hit_rate"],
                    "completed": cell["completed"],
                    "shed": cell["shed"],
                    "expired": cell["expired"],
                }
                for name, cell in rung["policies"].items()
            },
        }
        for rung in report["rungs"]
    ]
    print(json.dumps({
        "metric": "traffic_knee_rung",
        "value": None if knee is None else knee["rung"],
        "unit": "rung",
        "knee": knee,
        "traffic": {
            "schema": report["schema"],
            "arrival": report["arrival"]["spec"],
            "ladder": report["ladder"],
            "policies": report["policies"],
            "slo_spec": report["slo_spec"],
            "knee_objective": report["knee_objective"],
            "rungs": rungs,
        },
    }), flush=True)
    return 0


def multichip_inner() -> int:
    """Runs under the hermetic virtual-CPU env _attach_multichip sets up:
    a dp=4 mesh, one model/optimizer, and the trainer's exact update
    phase jitted twice — replicated and ``zero_dp`` — reporting per-device
    param/opt-state bytes and update-phase wall time for both. The bytes
    are layout facts (addressable-shard sums), valid on any backend; the
    update-phase ms is a CPU-relative comparison of the two programs.

    A second block (ISSUE 14) measures the serving side of the same
    story: one DecodeEngine at tp=1 vs tp=2 on the forced devices,
    reporting per-device KV-pool bytes (a layout fact: halved at tp=2
    when kv_heads divides) and decode-step / prefill wall time (CPU-
    relative, tp=2 pays virtual-device collective overhead here — the
    bytes are the claim, the times are the honesty check)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from mingpt_distributed_tpu.config import (
        GPTConfig, MeshConfig, OptimizerConfig,
    )
    from mingpt_distributed_tpu.models import gpt
    from mingpt_distributed_tpu.parallel import mesh as mesh_lib
    from mingpt_distributed_tpu.parallel import zero as zero_lib
    from mingpt_distributed_tpu.parallel.mesh import state_shardings
    from mingpt_distributed_tpu.training.optimizer import make_optimizer

    dp = 4
    mesh = mesh_lib.make_mesh(
        MeshConfig(dp=dp), devices=jax.devices()[:dp]
    )
    # big enough that moment bytes dominate scalar overheads, small enough
    # to stay seconds on CPU: ~3M params -> ~24 MB of fp32 Adam moments
    cfg = GPTConfig.make(
        n_layer=4, n_head=4, n_embd=256, vocab_size=512, block_size=64,
        embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0, dtype="float32",
    )
    optimizer = make_optimizer(OptimizerConfig(), grad_norm_clip=1.0)
    params_shape = jax.eval_shape(lambda: gpt.init(jax.random.key(0), cfg))
    plan = zero_lib.make_plan(mesh, params_shape)

    def measure(zero_plan):
        def init_state():
            params = gpt.init(jax.random.key(0), cfg)
            target = (
                zero_lib.update_view(params, zero_plan)
                if zero_plan is not None else params
            )
            return {
                "params": params,
                "opt_state": optimizer.init(target),
                "step": jnp.asarray(0, dtype=jnp.int32),
            }

        shardings = state_shardings(
            mesh, jax.eval_shape(init_state), zero_plan=zero_plan
        )
        state = jax.jit(init_state, out_shardings=shardings)()

        def update_only(state, grads):
            # the trainer's update phase verbatim (make_train_step minus
            # forward/backward), so the timed program is the real one
            if zero_plan is not None:
                gview = zero_lib.constrain(
                    zero_lib.update_view(grads, zero_plan), zero_plan
                )
                pview = zero_lib.constrain(
                    zero_lib.update_view(state["params"], zero_plan),
                    zero_plan,
                )
                updates, new_opt = optimizer.update(
                    gview, state["opt_state"], pview
                )
                new_params = zero_lib.from_view(
                    optax.apply_updates(pview, updates), zero_plan
                )
            else:
                updates, new_opt = optimizer.update(
                    grads, state["opt_state"], state["params"]
                )
                new_params = optax.apply_updates(state["params"], updates)
            return {
                "params": new_params, "opt_state": new_opt,
                "step": state["step"] + 1,
            }

        param_shardings = shardings["params"]
        grads = jax.jit(
            lambda p: jax.tree.map(lambda a: 1e-3 * a, p),
            out_shardings=param_shardings,
        )(state["params"])
        fn = jax.jit(
            update_only,
            in_shardings=(shardings, param_shardings),
            out_shardings=shardings,
        )
        for _ in range(2):
            state = fn(state, grads)
        jax.block_until_ready(state)
        n = 10
        t0 = time.perf_counter()
        for _ in range(n):
            state = fn(state, grads)
        jax.block_until_ready(state)
        dt = (time.perf_counter() - t0) / n
        assert np.isfinite(
            float(jax.device_get(jax.tree.leaves(state["params"])[0]).ravel()[0])
        )
        return {
            "param_bytes_per_device": zero_lib.per_device_bytes(
                state["params"]
            ),
            "opt_state_bytes_per_device": zero_lib.per_device_bytes(
                state["opt_state"]
            ),
            "update_ms": round(dt * 1e3, 2),
        }

    replicated = measure(None)
    sharded = measure(plan)

    # -- tensor-parallel sharded serving (ISSUE 14) --------------------
    from mingpt_distributed_tpu.serving.engine import DecodeEngine

    scfg = GPTConfig.make(
        n_layer=2, n_head=2, n_embd=64, vocab_size=128, block_size=32,
        embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0, dtype="float32",
    )

    def measure_serving(tp):
        serve_mesh = (
            mesh_lib.make_mesh(MeshConfig(tp=tp), devices=jax.devices()[:tp])
            if tp > 1 else None
        )
        eng = DecodeEngine(
            gpt.init(jax.random.key(0), scfg), scfg, n_slots=4,
            mesh=serve_mesh,
        )
        eng.warmup()
        key = jax.random.key(1)
        s = eng.n_slots
        tokens = np.zeros(s, np.int32)
        positions = np.full(s, scfg.block_size - 1, np.int32)
        temps = np.ones(s, np.float32)
        top_ks = np.zeros(s, np.int32)
        top_ps = np.ones(s, np.float32)
        greedy = np.zeros(s, bool)
        keys = jnp.stack([key] * s)
        n = 20
        t0 = time.perf_counter()
        for _ in range(n):
            eng.decode_step(
                tokens, positions, temps, top_ks, top_ps, greedy, keys)
        decode_ms = (time.perf_counter() - t0) / n * 1e3
        prompt = [1] * eng.prefill_len
        n = 10
        t0 = time.perf_counter()
        for _ in range(n):
            eng.prefill_chunk_call(
                0, prompt, 0, 1.0, None, None, False, key)
        prefill_ms = (time.perf_counter() - t0) / n * 1e3
        return {
            "kv_pool_bytes_per_device": zero_lib.per_device_bytes(
                eng.pool.cache
            ),
            "kv_pool_bytes_total": sum(
                int(a.nbytes) for a in eng.pool.cache.values()
            ),
            "decode_step_ms": round(decode_ms, 2),
            "prefill_ms": round(prefill_ms, 2),
        }

    serving_tp1 = measure_serving(1)
    serving_tp2 = measure_serving(2)

    print(json.dumps({
        "mesh": {"dp": dp},
        "n_devices": dp,
        "model": {"n_layer": cfg.n_layer, "n_embd": cfg.n_embd},
        "replicated": replicated,
        "zero_dp": sharded,
        "opt_bytes_ratio": round(
            sharded["opt_state_bytes_per_device"]
            / max(replicated["opt_state_bytes_per_device"], 1), 4
        ),
        "sharded_serving": {
            "tp1": serving_tp1,
            "tp2": serving_tp2,
            "kv_bytes_per_device_ratio": round(
                serving_tp2["kv_pool_bytes_per_device"]
                / max(serving_tp1["kv_pool_bytes_per_device"], 1), 4
            ),
        },
    }), flush=True)
    return 0


if __name__ == "__main__":
    if "--inner" in sys.argv:
        sys.exit(inner())
    if "--profile-inner" in sys.argv:
        sys.exit(profile_inner(sys.argv[sys.argv.index("--profile-inner") + 1]))
    if "--multichip-inner" in sys.argv:
        sys.exit(multichip_inner())
    if "--serving" in sys.argv:
        sys.exit(serving_inner())
    if "--traffic" in sys.argv:
        sys.exit(traffic_inner())
    sys.exit(main())
