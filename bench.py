#!/usr/bin/env python
"""Benchmark: GPT-2 124M training-step throughput + MFU on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

The reference publishes no numbers (SURVEY §6; BASELINE.json "published": {});
the driver-set north star is >=80% MFU on GPT-2 124M at seq 1024, so
``vs_baseline`` reports measured-MFU / 0.80.

The measured program is the full jitted training step (forward + backward +
AdamW update, donated state) — the same compiled unit the trainer runs, not a
matmul microbench.
"""

from __future__ import annotations

import json
import sys
import time


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mingpt_distributed_tpu.config import GPTConfig, OptimizerConfig
    from mingpt_distributed_tpu.models import gpt
    from mingpt_distributed_tpu.training.metrics import (
        flops_per_token,
        peak_flops_per_chip,
    )
    from mingpt_distributed_tpu.training.optimizer import make_optimizer
    from mingpt_distributed_tpu.training.trainer import make_train_step

    seq = 1024
    cfg = GPTConfig.make(
        model_type="gpt2",
        embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0,  # pure-compute bench
        dtype="bfloat16",
    )
    optimizer = make_optimizer(OptimizerConfig(), grad_norm_clip=1.0)
    step_fn = jax.jit(make_train_step(cfg, optimizer), donate_argnums=(0,))

    def try_batch(batch: int) -> float:
        """steps/sec for a given per-chip batch, or raise on OOM."""
        state = jax.jit(
            lambda k: {
                "params": gpt.init(k, cfg),
                "opt_state": optimizer.init(gpt.init(k, cfg)),
                "step": jnp.asarray(0, dtype=jnp.int32),
            }
        )(jax.random.key(0))
        # opt_state init duplicated gpt.init above only for tracing brevity;
        # XLA CSEs the two identical inits into one.
        tokens = jax.random.randint(
            jax.random.key(1), (batch, seq), 0, cfg.vocab_size, dtype=jnp.int32
        )
        rng = jax.random.key(2)
        # warmup (compile + 2 steps)
        for _ in range(3):
            state, m = step_fn(state, (tokens, tokens), rng)
        jax.block_until_ready(m)
        n_steps = 10
        t0 = time.perf_counter()
        for _ in range(n_steps):
            state, m = step_fn(state, (tokens, tokens), rng)
        jax.block_until_ready(m)
        dt = time.perf_counter() - t0
        return n_steps / dt

    result = None
    for batch in (16, 8, 4):
        try:
            sps = try_batch(batch)
            result = (batch, sps)
            break
        except Exception as e:  # noqa: BLE001 — OOM/backend errors: try smaller
            msg = str(e)
            if "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg.lower():
                continue
            raise
    if result is None:
        print(json.dumps({"metric": "mfu_gpt2_124m_seq1024", "value": 0.0,
                          "unit": "fraction", "vs_baseline": 0.0,
                          "error": "all batch sizes OOM"}))
        return 1

    batch, steps_per_sec = result
    tokens_per_sec = steps_per_sec * batch * seq
    fpt = flops_per_token(cfg, seq)
    peak = peak_flops_per_chip()
    achieved = tokens_per_sec * fpt
    mfu = achieved / peak if peak else None

    dev = jax.devices()[0]
    record = {
        "metric": "mfu_gpt2_124m_seq1024",
        "value": round(mfu, 4) if mfu is not None else None,
        "unit": "fraction",
        # north-star target is 0.80 MFU (BASELINE.md) — no reference-published
        # number exists, so the baseline is the target
        "vs_baseline": round(mfu / 0.80, 4) if mfu is not None else None,
        "tokens_per_sec_per_chip": round(tokens_per_sec, 1),
        "flops_per_token": fpt,
        "achieved_tflops": round(achieved / 1e12, 2),
        "peak_tflops": round(peak / 1e12, 1) if peak else None,
        "batch": batch,
        "seq": seq,
        "device": dev.device_kind,
        "n_devices": jax.device_count(),
    }
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
